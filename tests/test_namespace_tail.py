"""Optimizer/distribution/fft/vision namespace tail vs torch/scipy
references, plus closure checks for those reference export lists."""

import numpy as np
import pytest
import scipy.fft
import scipy.stats
import torch

import paddlepaddle_tpu as paddle

rng = np.random.default_rng(0)


def test_new_optimizers_train():
    x = rng.standard_normal((8, 4)).astype(np.float32)
    for name in ("ASGD", "NAdam", "RAdam", "Rprop"):
        paddle.seed(0)
        lin = paddle.nn.Linear(4, 1)
        opt = getattr(paddle.optimizer, name)(learning_rate=0.01,
                                              parameters=lin.parameters())
        first = last = None
        for _ in range(10):
            loss = ((lin(x) - 1.0) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss.numpy())
            last = float(loss.numpy())
        assert last < first, name


def test_nadam_matches_torch():
    import jax.numpy as jnp

    w0 = np.array([1.5, -2.0], np.float32)
    g_seq = [np.array([0.3, -0.1], np.float32),
             np.array([-0.2, 0.4], np.float32),
             np.array([0.1, 0.1], np.float32)]
    tw = torch.tensor(w0.copy(), requires_grad=True)
    topt = torch.optim.NAdam([tw], lr=0.01)
    pw = paddle.to_tensor(w0.copy(), stop_gradient=False)
    popt = paddle.optimizer.NAdam(learning_rate=0.01, parameters=[pw])
    for g in g_seq:
        tw.grad = torch.tensor(g)
        topt.step()
        pw._grad = jnp.asarray(g)
        popt.step()
        popt.clear_grad()
    np.testing.assert_allclose(pw.numpy(), tw.detach().numpy(), rtol=1e-5)


def test_binomial_and_mvn_vs_scipy():
    from paddlepaddle_tpu.distribution import Binomial, MultivariateNormal

    b = Binomial(10, 0.3)
    np.testing.assert_allclose(b.log_prob(np.float32(3)).numpy(),
                               scipy.stats.binom.logpmf(3, 10, 0.3),
                               rtol=1e-5)
    assert abs(float(b.mean.numpy()) - 3.0) < 1e-6

    mvn = MultivariateNormal(np.zeros(2, np.float32),
                             np.array([[2.0, 0.5], [0.5, 1.0]], np.float32))
    np.testing.assert_allclose(
        mvn.log_prob(np.array([0.5, -0.5], np.float32)).numpy(),
        scipy.stats.multivariate_normal([0, 0],
                                        [[2, .5], [.5, 1]]).logpdf([0.5, -0.5]),
        rtol=1e-5)
    sm = mvn.sample([4000]).numpy()
    np.testing.assert_allclose(np.cov(sm.T), [[2, .5], [.5, 1]], atol=0.2)
    np.testing.assert_allclose(
        mvn.entropy().numpy(),
        scipy.stats.multivariate_normal([0, 0], [[2, .5], [.5, 1]]).entropy(),
        rtol=1e-5)


def test_independent_and_lkj_and_cb():
    from paddlepaddle_tpu.distribution import (ContinuousBernoulli,
                                               Independent, LKJCholesky,
                                               Normal)

    ind = Independent(Normal(np.zeros(3, np.float32),
                             np.ones(3, np.float32)), 1)
    np.testing.assert_allclose(ind.log_prob(np.zeros(3, np.float32)).numpy(),
                               3 * scipy.stats.norm.logpdf(0), rtol=1e-5)

    L = LKJCholesky(3, 2.0).sample().numpy()
    np.testing.assert_allclose(np.diag(L @ L.T), np.ones(3), atol=1e-5)
    assert np.isfinite(
        LKJCholesky(3, 2.0).log_prob(L.astype(np.float32)).numpy())

    cb = ContinuousBernoulli(np.float32(0.3))
    grid = np.linspace(1e-4, 1 - 1e-4, 2001).astype(np.float32)
    dens = np.exp(cb.log_prob(grid).numpy())
    np.testing.assert_allclose(np.trapezoid(dens, grid), 1.0, rtol=1e-3)
    s = cb.sample([500]).numpy()
    assert (s >= 0).all() and (s <= 1).all()


def test_hermitian_fft_family_vs_scipy():
    a = (rng.standard_normal((4, 5))
         + 1j * rng.standard_normal((4, 5))).astype(np.complex64)
    r = rng.standard_normal((4, 8)).astype(np.float32)
    np.testing.assert_allclose(paddle.fft.hfft2(a).numpy(),
                               scipy.fft.hfft2(a), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(paddle.fft.ihfft2(r).numpy(),
                               scipy.fft.ihfft2(r), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(paddle.fft.hfftn(a).numpy(),
                               scipy.fft.hfftn(a), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(paddle.fft.ihfftn(r).numpy(),
                               scipy.fft.ihfftn(r), rtol=1e-4, atol=1e-6)


def test_vision_backend_helpers(tmp_path):
    paddle.vision.set_image_backend("pil")
    assert paddle.vision.get_image_backend() == "pil"
    with pytest.raises(ValueError):
        paddle.vision.set_image_backend("bogus")
    from PIL import Image

    p = tmp_path / "img.png"
    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(p)
    img = paddle.vision.image_load(str(p))
    assert img.size == (4, 4)
    t = paddle.vision.image_load(str(p), backend="tensor")
    assert t.shape == [4, 4, 3]


def test_module_namespaces_closed():
    import os
    import re

    if not os.path.exists("/root/reference"):
        pytest.skip("reference tree not present")
    for path, mod in [
        ("/root/reference/python/paddle/optimizer/__init__.py",
         paddle.optimizer),
        ("/root/reference/python/paddle/distribution/__init__.py",
         paddle.distribution),
        ("/root/reference/python/paddle/vision/__init__.py", paddle.vision),
        ("/root/reference/python/paddle/io/__init__.py", paddle.io),
        ("/root/reference/python/paddle/metric/__init__.py", paddle.metric),
    ]:
        ref = set(re.findall(r"'(\w+)'", open(path).read()))
        missing = sorted(n for n in ref
                         if not hasattr(mod, n) and not n.startswith("_"))
        assert missing == [], f"{path}: {missing}"


def test_top_level_namespace_closed():
    """EVERY name in the reference's top-level __all__
    (python/paddle/__init__.py) resolves here — 438/438 as of round 4
    (dtype/bool/pstring/raw/batch/index_*_ closed the last 8)."""
    import ast
    import os

    path = "/root/reference/python/paddle/__init__.py"
    if not os.path.exists(path):
        import pytest as _pytest

        _pytest.skip("reference tree not present")
    tree = ast.parse(open(path).read())
    ref_all = [e.value for node in ast.walk(tree)
               if isinstance(node, ast.Assign)
               for t in node.targets
               if isinstance(t, ast.Name) and t.id == "__all__"
               for e in ast.walk(node.value)
               if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    assert len(ref_all) > 400
    missing = sorted(n for n in ref_all if not hasattr(paddle, n))
    assert missing == [], missing


def test_fleet_submodule_import_paths():
    """The import paths reference training scripts actually use
    (fleet/meta_parallel, fleet/utils, fleet/meta_optimizers) resolve to
    the real implementations."""
    from paddlepaddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, LayerDesc, PipelineLayer, RowParallelLinear,
        SharedLayerDesc, VocabParallelEmbedding, get_rng_state_tracker)
    from paddlepaddle_tpu.distributed.fleet.meta_optimizers import LocalSGD
    from paddlepaddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer import (
        HybridParallelGradScaler, HybridParallelOptimizer)
    from paddlepaddle_tpu.distributed.fleet.utils import recompute
    from paddlepaddle_tpu.parallel.pipeline import LayerDesc as LD

    assert LayerDesc is LD                     # shim, not a copy
    import numpy as np

    lin = paddle.nn.Linear(2, 2)
    opt = HybridParallelOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters()))
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    loss = (lin(x) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(loss.numpy()))
    scaler = HybridParallelGradScaler(init_loss_scaling=8.0)
    assert scaler is not None


def test_round4_namespace_additions():
    """signal/regularizer/callbacks/device/nn.utils/nn.quant/vision.ops/
    static.nn/fleet.base import paths (reference user-script surface)."""
    import numpy as np

    import paddlepaddle_tpu.device as dev
    import paddlepaddle_tpu.signal as sig
    from paddlepaddle_tpu.callbacks import EarlyStopping  # noqa: F401
    from paddlepaddle_tpu.distributed.fleet.base.role_maker import (
        PaddleCloudRoleMaker)
    from paddlepaddle_tpu.nn.quant import weight_dequantize, weight_quantize
    from paddlepaddle_tpu.nn.utils import (parameters_to_vector,
                                           vector_to_parameters, weight_norm)
    from paddlepaddle_tpu.regularizer import L1Decay, L2Decay
    from paddlepaddle_tpu.vision.ops import box_coder, nms, roi_align

    assert not dev.cuda.is_available() and dev.cuda.device_count() == 0
    x = paddle.to_tensor(np.random.randn(1, 128).astype(np.float32))
    assert sig.stft(x, n_fft=32).shape[1] == 17
    assert PaddleCloudRoleMaker().worker_num() >= 1

    # L1 vs L2 decay fold semantics through a real SGD step
    for reg, expect in ((L1Decay(0.1), lambda w: w - 0.01 * np.sign(w)),
                        (L2Decay(0.1), lambda w: w - 0.01 * w)):
        lin = paddle.nn.Linear(3, 2)
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1, weight_decay=reg,
                                   parameters=[lin.weight])
        lin.weight._grad = paddle.to_tensor(np.zeros_like(w0))
        opt.step()
        np.testing.assert_allclose(lin.weight.numpy(), expect(w0),
                                   rtol=1e-5, atol=1e-6)

    # weight_norm: identity at init, g rescales, vector roundtrip
    lin = paddle.nn.Linear(4, 3)
    w0 = lin.weight.numpy().copy()
    weight_norm(lin, "weight", dim=0)
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5, atol=1e-6)
    vec = parameters_to_vector(lin.parameters())
    vector_to_parameters(vec, lin.parameters())

    # int8 weight quantize roundtrip
    w = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
    q, s = weight_quantize(w)
    back = weight_dequantize(q, s)
    assert np.abs(back.numpy() - w.numpy()).max() < 0.05

    # nms + box_coder basics
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                       np.float32)
    keep = nms(paddle.to_tensor(boxes), 0.3,
               paddle.to_tensor(np.asarray([0.9, 0.8, 0.7], np.float32)))
    assert keep.numpy().tolist() == [0, 2]
    feat = paddle.to_tensor(np.ones((1, 2, 8, 8), np.float32))
    rois = paddle.to_tensor(np.asarray([[0, 0, 4, 4]], np.float32))
    out = roi_align(feat, rois, paddle.to_tensor(np.asarray([1], np.int32)), 2)
    np.testing.assert_allclose(out.numpy(), 1.0, rtol=1e-5)


def test_static_nn_fc_trains():
    """The reference's canonical static fc example under the replay
    executor (static/nn/common.py fc)."""
    import numpy as np

    paddle.enable_static()
    try:
        import paddlepaddle_tpu.static as static
        from paddlepaddle_tpu.static.nn import fc

        x = static.data("x", [4, 3], "float32")
        y = static.data("y", [4, 1], "float32")
        pred = fc(fc(x, 8, activation="relu"), 1)
        loss = ((pred - y) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.05)
        opt.minimize(loss)
        exe = static.Executor()
        rng = np.random.default_rng(0)
        xv = rng.standard_normal((4, 3)).astype(np.float32)
        yv = np.ones((4, 1), np.float32)
        losses = [float(exe.run(feed={"x": xv, "y": yv},
                                fetch_list=[loss])[0]) for _ in range(5)]
        assert losses[-1] < losses[0], losses
    finally:
        paddle.disable_static()



def test_major_submodule_namespaces_closed():
    """nn / nn.functional / distributed / incubate __all__ closure vs the
    reference (438-name top-level closure is the sibling test)."""
    import ast
    import os

    def ref_all(path):
        tree = ast.parse(open(path).read())
        return [e.value for n in ast.walk(tree) if isinstance(n, ast.Assign)
                for t in n.targets
                if isinstance(t, ast.Name) and t.id == "__all__"
                for e in ast.walk(n.value)
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]

    base = "/root/reference/python/paddle"
    if not os.path.exists(base):
        import pytest as _pytest

        _pytest.skip("reference tree not present")
    for rel, mod in [("nn/__init__.py", paddle.nn),
                     ("nn/functional/__init__.py", paddle.nn.functional),
                     ("distributed/__init__.py", paddle.distributed),
                     ("incubate/__init__.py", paddle.incubate),
                     ("static/__init__.py", paddle.static),
                     ("vision/ops.py", paddle.vision.ops),
                     ("sparse/__init__.py", paddle.sparse),
                     ("jit/__init__.py", paddle.jit),
                     ("autograd/__init__.py", paddle.autograd),
                     ("amp/__init__.py", paddle.amp),
                     ("fft.py", paddle.fft),
                     ("signal.py", paddle.signal)]:
        ra = ref_all(f"{base}/{rel}")
        missing = sorted(n for n in ra if not hasattr(mod, n))
        assert missing == [], f"{rel}: {missing}"


def test_matrix_nms_and_generate_proposals():
    """Matrix-NMS decay math (SOLOv2 eq. 3: linear decay with suppressor
    compensation) and the RPN proposal pipeline (decode/clip/filter/nms)."""
    import numpy as np

    from paddlepaddle_tpu.vision.ops import generate_proposals, matrix_nms

    bboxes = np.asarray([[[0, 0, 10, 10], [1, 1, 11, 11], [30, 30, 40, 40]]],
                        np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.85, 0.8]
    out, rois, index = matrix_nms(paddle.to_tensor(bboxes),
                                  paddle.to_tensor(scores),
                                  score_threshold=0.1)
    assert index is None
    o = out.numpy()
    assert int(rois.numpy()[0]) == 3
    # rows sorted by decayed score: top box undecayed, far box undecayed,
    # the overlapping box decayed by (1-iou)/(1-0) * 0.85
    np.testing.assert_allclose(o[0, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(o[1, 1], 0.8, rtol=1e-5)
    iou = (9 * 9) / (10 * 10 + 10 * 10 - 9 * 9)
    np.testing.assert_allclose(o[2, 1], 0.85 * (1 - iou), rtol=1e-4)

    H = W = 2
    anchors = np.zeros((H, W, 1, 4), np.float32)
    for y in range(H):
        for x in range(W):
            anchors[y, x, 0] = [x * 8, y * 8, x * 8 + 12, y * 8 + 12]
    sc = np.random.default_rng(0).random((1, 1, H, W)).astype(np.float32)
    rois2, probs, num = generate_proposals(
        paddle.to_tensor(sc),
        paddle.to_tensor(np.zeros((1, 4, H, W), np.float32)),
        paddle.to_tensor(np.asarray([[32, 32]], np.float32)),
        paddle.to_tensor(anchors), paddle.to_tensor(np.ones_like(anchors)),
        nms_thresh=0.9)
    n = int(num.numpy()[0])
    assert rois2.shape[0] == n > 0 and list(probs.shape) == [n, 1]
    # zero deltas: proposals are the (clipped) anchors themselves
    assert rois2.numpy().max() <= 32.0


def test_psroi_pool_position_sensitive():
    """psroi_pool (R-FCN): channel block (i, j) pools ONLY spatial bin
    (i, j) — verified with distinct per-block constants."""
    import numpy as np

    from paddlepaddle_tpu.vision.ops import PSRoIPool, psroi_pool

    oh = ow = 2
    out_c = 3
    C = out_c * oh * ow
    feat = np.zeros((1, C, 8, 8), np.float32)
    for c in range(C):
        feat[0, c] = c + 1
    rois = paddle.to_tensor(np.asarray([[0, 0, 8, 8]], np.float32))
    bn = paddle.to_tensor(np.asarray([1], np.int32))
    out = psroi_pool(paddle.to_tensor(feat), rois, bn, 2).numpy()
    for k in range(out_c):
        for i in range(oh):
            for j in range(ow):
                assert out[0, k, i, j] == k * oh * ow + i * ow + j + 1
    np.testing.assert_allclose(
        PSRoIPool(2)(paddle.to_tensor(feat), rois, bn).numpy(), out)
    import pytest as _p

    with _p.raises(ValueError, match="divisible"):
        psroi_pool(paddle.to_tensor(feat[:, :5]), rois, bn, 2)
