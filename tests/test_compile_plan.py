"""Compile plan + persistent compile cache + AOT serving bundles
(inference/compile_plan.py, core/compile_cache.py, engine warmup/bundle
surfaces, router pre-warm).

The acceptance surface of the cold-start work: the plan enumerates exactly
what the engine compiles (watchdog-counted), warmup leaves ZERO compiles
in the serve window, a bundle save->load round trip is token-exact vs a
fresh engine with zero retraces on the bundle path, a manifest mismatch
falls back cleanly (never crashes), persistent-cache hits are labeled by
the recompile watchdog (warm restarts don't read as storms), and
rolling_restart pre-warms a replica before re-admission."""

import json
import os

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.core import compile_cache
from paddlepaddle_tpu.inference import compile_plan as cp
from paddlepaddle_tpu.inference.decode_engine import BatchDecodeEngine
from paddlepaddle_tpu.inference.serving import GenerationRequest, ServingEngine
from paddlepaddle_tpu.observability import watchdog


def _model(dtype="bfloat16"):
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=192,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=96, dtype=dtype))


@pytest.fixture(scope="module")
def model():
    return _model()


@pytest.fixture(scope="module")
def warm_engine(model):
    """One warmed bf16 engine shared by the fast tests (params are
    read-only, so engines built over the same model are weight-identical
    — the bundle parity baseline)."""
    watchdog.install(threshold=3)
    eng = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16)
    eng.warmup()
    return eng


def _reqs(n=2, toks=6):
    return [GenerationRequest([1, 2, 3, 4, 5], toks, 0.0, 0, None)
            for _ in range(n)]


def _serve(eng, reqs):
    eng.serve(reqs, timeout=120)
    return [np.asarray(r.result.result(5)) for r in reqs]


def _total_compiles():
    return sum(watchdog.compile_counts().values())


def _cold_compiles():
    return sum(watchdog.cold_compile_counts().values())


# -- units -------------------------------------------------------------------

def test_key_helpers_and_prompt_buckets():
    assert cp.prompt_buckets(96) == [96]
    assert cp.prompt_buckets(256) == [128, 256]
    assert cp.prompt_buckets(300) == [128, 256, 300]
    assert cp.parse_key(cp.decode_key()) == ("decode", {})
    assert cp.parse_key(cp.admit_key(128)) == ("admit", {"bucket": 128})
    assert cp.parse_key(cp.prefix_admit_key(2, 64)) == (
        "prefix", {"n_pfx": 2, "tail_bucket": 64})
    with pytest.raises(ValueError, match="unrecognized"):
        cp.parse_key("admit_banana")
    with pytest.raises(ValueError, match="unrecognized"):
        cp.parse_key("../../etc/passwd")


def test_plan_enumeration_and_fingerprint(warm_engine):
    plan = warm_engine.compile_plan
    assert plan.keys() == ["decode", "admit_p96"]
    facts = plan.facts
    assert facts["quant"] == "off" and facts["kv_layout"] == "paged"
    assert facts["page_size"] == 16 and facts["max_len"] == 96
    # stable: re-deriving the plan from the same engine fingerprints equal
    assert cp.CompilePlan.for_engine(warm_engine).fingerprint() \
        == plan.fingerprint()
    d = plan.describe()
    assert d["entries"] == 2 and len(d["fingerprint"]) == 16


# -- warmup: eager plan compile, compile-free serve window -------------------

def test_warmup_compiles_plan_and_serve_window_is_compile_free(warm_engine):
    # the module fixture already warmed; re-warm must be a no-op
    info = warm_engine.warmup()
    assert info["compiled"] == 0 and info["skipped"] == len(
        warm_engine.compile_plan.keys())
    assert set(warm_engine.compile_plan.keys()) <= set(
        warm_engine._programs)
    before = _total_compiles()
    outs = _serve(warm_engine, _reqs())
    assert all(len(o) == 11 for o in outs)          # 5 prompt + 6 new
    assert _total_compiles() == before, \
        "warmup must leave zero compiles in the serve window"
    # greedy determinism across engines is the parity baseline below
    assert (outs[0] == outs[1]).all()


def test_lazy_build_stays_inside_the_plan(model):
    eng = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16)
    _serve(eng, _reqs())
    assert set(eng._programs) <= set(eng.compile_plan.keys()), \
        "the engine compiled a program its plan does not enumerate"


# -- bundles -----------------------------------------------------------------

def test_bundle_round_trip_token_exact_zero_retrace(warm_engine, model,
                                                    tmp_path):
    path = str(tmp_path / "bundle")
    manifest = warm_engine.save_serving_bundle(path)
    assert {e["key"] for e in manifest["entries"]} == {"decode",
                                                       "admit_p96"}
    assert os.path.exists(os.path.join(path, "manifest.json"))
    baseline = _serve(warm_engine, _reqs())
    before = _cold_compiles()
    eng2 = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16,
                             bundle=path)
    assert eng2._bundle_info["loaded"] is True
    assert eng2._bundle_info["programs"] == 2
    outs = _serve(eng2, _reqs())
    assert _cold_compiles() == before, \
        "bundle path must serve with zero retraces/compiles"
    for a, b in zip(baseline, outs):
        assert (a == b).all(), "bundle-loaded engine diverged token-wise"
    info = eng2.compile_info()
    assert info["bundle"]["loaded"] and info["programs_built"] == 2
    assert info["plan"]["fingerprint"] == manifest["fingerprint"][:16]


def test_bundle_mismatch_and_corruption_fall_back(warm_engine, model,
                                                  tmp_path):
    path = str(tmp_path / "bundle_m")
    warm_engine.save_serving_bundle(path)
    # config mismatch (different page geometry) -> logged fallback, the
    # engine builds lazily and still serves
    eng = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=32,
                            bundle=path)
    assert eng._bundle_info["loaded"] is False
    assert "page_size" in eng._bundle_info["error"] \
        or "fingerprint" in eng._bundle_info["error"]
    assert eng._programs == {}            # nothing half-loaded
    outs = _serve(eng, _reqs(n=1))
    assert len(outs[0]) == 11
    # strict load surfaces the typed error
    with pytest.raises(cp.BundleMismatchError):
        eng.load_serving_bundle(path, strict=True)
    # corruption: flip bytes in one payload -> sha check rejects, engine
    # keeps its (already working) programs
    victim = next(f for f in os.listdir(path) if f.endswith(".xc"))
    with open(os.path.join(path, victim), "r+b") as f:
        f.write(b"\x00garbage\x00")
    eng3 = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16,
                             bundle=path)
    assert eng3._bundle_info["loaded"] is False
    assert "sha256" in eng3._bundle_info["error"]


# -- persistent compile cache + watchdog labeling ----------------------------

def test_compile_cache_hits_and_watchdog_labels(model, tmp_path):
    cache_dir = str(tmp_path / "ccache")
    watchdog.install(threshold=3)   # order-independent of the fixtures
    watchdog.reset()
    storms = []
    watchdog.set_storm_callback(lambda site, n: storms.append(site))
    assert compile_cache.install(cache_dir) is True
    try:
        e1 = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16)
        w1 = e1.warmup()
        assert w1["compiled"] == 2 and w1["cache_hits"] == 0
        stats = compile_cache.stats()
        assert stats["enabled"] and stats["misses"] >= 2
        # a SECOND engine re-jits the same programs: persistent cache
        # serves them, the watchdog labels them hits, and no per-callsite
        # storm fires on this warm "restart"
        e2 = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16)
        w2 = e2.warmup()
        assert w2["compiled"] == 2 and w2["cache_hits"] >= 2
        stats = compile_cache.stats()
        assert stats["hits"] >= 2 and stats["retrieval_s"] > 0
        assert sum(watchdog.cache_hit_counts().values()) >= 2
        assert sum(watchdog.cold_compile_counts().values()) \
            < sum(watchdog.compile_counts().values())
        assert not storms, f"warm restart tripped storm warnings: {storms}"
        log = watchdog.compile_log()
        assert any(e.get("cache_hit") for e in log)
        assert any(e.get("planned") == "warmup" for e in log)
        outs = _serve(e2, _reqs(n=1))
        assert len(outs[0]) == 11
        # a bundle saved from the HIT engine must load back: e2's
        # executables are cache-DESERIALIZED, and re-serializing those
        # yields payloads with no kernel object code on this jaxlib's
        # CPU backend ("Symbols not found" at load). save_bundle probes
        # every payload and recompiles for real, cache detached
        hit_path = str(tmp_path / "hit_bundle")
        e2.save_serving_bundle(hit_path)
        e3 = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16,
                               bundle=hit_path)
        assert e3._bundle_info["loaded"] is True, \
            e3._bundle_info.get("error")
    finally:
        compile_cache.uninstall()
        watchdog.set_storm_callback(None)
    assert compile_cache.stats()["enabled"] is False
    # uninstall must DETACH, not just stop counting: jax latches its
    # cache handle + "cache used" decision at the first compile, and a
    # stale latch keeps the old directory serving hits and absorbing
    # writes for the rest of the process (the ordering bug that poisoned
    # later engines' bundle saves with cache-deserialized executables)
    from jax._src import compilation_cache as _jcc

    assert _jcc._cache is None, \
        "uninstall left jax's latched persistent-cache handle live"


def test_compile_cache_flag_family():
    from paddlepaddle_tpu.core import flags

    assert flags.flag_value("compile_cache_dir") == ""
    assert flags.flag_value("compile_cache_min_compile_secs") == 0.0
    # empty dir -> install refuses (cache stays off)
    assert compile_cache.install("") is False


# -- serving engine + health surfaces ----------------------------------------

def test_serving_health_compile_block_and_static_mode(model):
    eng = ServingEngine(model, mode="static", max_batch_size=2)
    h = eng.health()
    assert "compile" in h and "cache" in h["compile"]
    # static mode: warmup is a documented no-op, bundles are refused
    assert eng.warmup()["mode"] == "static"
    with pytest.raises(ValueError, match="continuous"):
        eng.save_serving_bundle("/tmp/nope")
    with pytest.raises(ValueError, match="continuous"):
        ServingEngine(model, mode="static", bundle="/tmp/nope")


def test_serving_engine_bundle_passthrough(warm_engine, model, tmp_path):
    path = str(tmp_path / "bundle_se")
    warm_engine.save_serving_bundle(path)
    srv = ServingEngine(model, mode="continuous", max_batch_size=2,
                        decode_chunk=4, kv_page_size=16, bundle=path)
    h = srv.health()
    assert h["compile"]["bundle"]["loaded"] is True
    assert h["compile"]["plan"]["entries"] == 2
    before = _cold_compiles()
    with srv:
        out = srv.generate([1, 2, 3, 4, 5], max_new_tokens=4,
                           timeout=120)
    assert len(out) == 9 and _cold_compiles() == before


# -- router pre-warm ---------------------------------------------------------

def test_rolling_restart_prewarms_before_readmission(model, tmp_path):
    from paddlepaddle_tpu.inference.router import ServingRouter

    assert compile_cache.install(str(tmp_path / "rcache"))
    try:
        def factory():
            return ServingEngine(model, mode="continuous",
                                 max_batch_size=2, decode_chunk=4,
                                 kv_page_size=16)

        router = ServingRouter([factory], probe_interval_s=0.05)
        with router:
            out = router.generate([1, 2, 3, 4, 5], max_new_tokens=4,
                                  timeout=120)
            assert len(out) == 9
            res = router.rolling_restart(health_timeout=30.0)
            assert res["ok"] is True
            warm = res["replicas"][0]["warmup"]
            # the fresh engine's whole plan compiled OUT of rotation...
            assert warm is not None and warm["compiled"] == 2
            # ...so the first routed request finds only warm programs
            before = _total_compiles()
            out2 = router.generate([1, 2, 3, 4, 5], max_new_tokens=4,
                                   timeout=120)
            assert len(out2) == 9
            assert _total_compiles() == before, \
                "first request after rolling restart hit a cold program"
    finally:
        compile_cache.uninstall()


# -- perf gate ---------------------------------------------------------------

def test_perf_gate_coldstart_metrics(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import perf_gate

    base = {"coldstart": {
        "restart_to_first_token_s": 1.0, "compiles": 0,
        "cold": {"restart_to_first_token_s": 20.0},
        "bundle": {"restart_to_first_token_s": 1.0},
        "bundle_cache": {"restart_to_first_token_s": 0.6}}}
    good = json.loads(json.dumps(base))
    bad = {"coldstart": {
        "restart_to_first_token_s": 4.0, "compiles": 5,
        "cold": {"restart_to_first_token_s": 20.0},
        "bundle": {"restart_to_first_token_s": 4.0},
        "bundle_cache": {"restart_to_first_token_s": 4.0}}}
    bench = str(tmp_path / "bench.json")
    with open(bench, "w") as f:
        json.dump({"value": 100.0}, f)
    paths = {}
    for name, doc in (("base", base), ("good", good), ("bad", bad)):
        paths[name] = str(tmp_path / f"{name}.json")
        with open(paths[name], "w") as f:
            json.dump(doc, f)
    assert perf_gate.main(["--baseline", bench, "--serving",
                           paths["good"], paths["base"]]) == 0
    rc = perf_gate.main(["--baseline", bench, "--serving",
                         paths["bad"], paths["base"]])
    assert rc == 1          # slower restart AND compiles off the 0 floor
    # the metric extraction itself
    m = perf_gate.serving_metrics(bad)
    assert m["coldstart.restart_to_first_token_s"] == (4.0, "lower")
    assert m["coldstart.compiles"] == (5.0, "lower")
    assert m["coldstart.bundle.restart_to_first_token_s"][1] == "lower"


# -- full e2e: int8 + prefix variants (slow) ---------------------------------

@pytest.mark.slow
def test_bundle_full_e2e_int8_with_prefix_variant(tmp_path):
    # BOTH phases in fresh subprocesses — the real deploy shape (a
    # bundle-save job, then a restarted serving process). In-process,
    # earlier suite tests that *executed* persistent-cache-retrieved
    # executables leave XLA CPU symbol state that makes executables
    # serialized afterwards non-portable (`Symbols not found` at
    # deserialize) — the graceful-fallback path, see docs/serving.md.
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = str(tmp_path / "bundle_int8")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # phase 1: build the int8 engine, drive prefix traffic so the
    # traffic-shaped admit_pfx program exists (not in the static plan,
    # but bundled once built), save the bundle
    saver = (
        "import json, sys\n"
        "sys.path.insert(0, %r)\n"
        "import tests.test_compile_plan as t\n"
        "from paddlepaddle_tpu.inference.decode_engine import "
        "BatchDecodeEngine\n"
        "from paddlepaddle_tpu.inference.serving import GenerationRequest\n"
        "from paddlepaddle_tpu.observability import watchdog\n"
        "watchdog.install()\n"
        "m = t._model()\n"
        "eng = BatchDecodeEngine(m, max_slots=2, chunk=4, page_size=16,\n"
        "    quant='weight_only_int8', quant_group_size=16)\n"
        "eng.warmup()\n"
        "prompt = list(range(1, 41))\n"
        "r1 = GenerationRequest(prompt, 5, 0.0, 0, None, prefix_len=20)\n"
        "r2 = GenerationRequest(prompt[:20] + list(range(50, 70)), 5, 0.0,"
        " 0, None, prefix_len=20)\n"
        "outs = t._serve(eng, [r1, r2])\n"
        "pfx = [k for k in eng._programs if k.startswith('admit_pfx')]\n"
        "assert pfx, 'prefix traffic did not build a prefix-HIT program'\n"
        "manifest = eng.save_serving_bundle(%r)\n"
        "saved = {e['key'] for e in manifest['entries']}\n"
        "assert set(eng.compile_plan.keys()) | set(pfx) <= saved\n"
        "print(json.dumps({'tokens': [o.tolist() for o in outs],\n"
        "                  'saved': sorted(saved)}))\n"
    ) % (root, path)
    proc = subprocess.run([sys.executable, "-c", saver], env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    base = json.loads(proc.stdout.strip().splitlines()[-1])["tokens"]
    # phase 2: fresh same-weights engine restarted from the bundle
    child = (
        "import json, sys, numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "import tests.test_compile_plan as t\n"
        "from paddlepaddle_tpu.inference.decode_engine import "
        "BatchDecodeEngine\n"
        "from paddlepaddle_tpu.inference.serving import GenerationRequest\n"
        "from paddlepaddle_tpu.observability import watchdog\n"
        "watchdog.install()\n"
        "m = t._model()\n"
        "eng = BatchDecodeEngine(m, max_slots=2, chunk=4, page_size=16,\n"
        "    quant='weight_only_int8', quant_group_size=16, bundle=%r)\n"
        "eng.load_serving_bundle(%r, strict=True)  # loud on mismatch\n"
        "w = eng.warmup()  # flushes host-op fills; programs all loaded\n"
        "c0 = sum(watchdog.compile_counts().values())\n"
        "prompt = list(range(1, 41))\n"
        "r3 = GenerationRequest(prompt, 5, 0.0, 0, None, prefix_len=20)\n"
        "r4 = GenerationRequest(prompt[:20] + list(range(50, 70)), 5, 0.0,"
        " 0, None, prefix_len=20)\n"
        "outs = t._serve(eng, [r3, r4])\n"
        "print(json.dumps({\n"
        "    'loaded': eng._bundle_info['loaded'],\n"
        "    'warmup_compiled': w['compiled'],\n"
        "    'serve_window_compiles':\n"
        "        sum(watchdog.compile_counts().values()) - c0,\n"
        "    'prefix_hits': eng.prefix.hits,\n"
        "    'tokens': [o.tolist() for o in outs]}))\n"
    ) % (root, path, path)
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["loaded"] is True
    # zero retraces on the bundle path: every plan program came from the
    # bundle (warmup had nothing to compile) and the serve window —
    # including the bundled prefix-HIT program — is compile-free
    assert out["warmup_compiled"] == 0
    assert out["serve_window_compiles"] == 0
    assert out["prefix_hits"] >= 1
    assert out["tokens"] == base, \
        "bundle-restarted engine diverged token-wise from the saver"


# -- fused-kernel programs through the cold-start machinery ------------------

def test_fused_program_warmup_bundle_round_trip_and_mismatch(model,
                                                             tmp_path):
    """ISSUE 15 satellite: the fused paged-decode program is a first-
    class CompilePlan citizen — warmup() still guarantees a compile-free
    serve window with the kernel armed, a bundle round-trips the fused
    program with zero cold compiles, and a kernel-config mismatch
    (bundle saved fused, engine resolved reference — or vice versa)
    falls back LOUDLY with the differing fact named."""
    watchdog.install(threshold=3)
    eng = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16,
                            fused_kernels=True)
    assert eng.compile_plan.facts["fused"] == {"paged_attention": "fused"}
    info = eng.warmup()
    assert info["compiled"] == len(eng.compile_plan.keys())
    before = _total_compiles()
    baseline = _serve(eng, _reqs())
    assert _total_compiles() == before, \
        "warmup must leave zero compiles in the fused serve window"

    path = str(tmp_path / "fused_bundle")
    eng.save_serving_bundle(path)
    cold0 = _cold_compiles()
    eng2 = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16,
                             fused_kernels=True, bundle=path)
    assert eng2._bundle_info["loaded"] is True
    outs = _serve(eng2, _reqs())
    assert _cold_compiles() == cold0, \
        "fused bundle path must serve with zero cold compiles"
    for a, b in zip(baseline, outs):
        assert (a == b).all()

    # kernel-config mismatch: a REFERENCE engine must reject the fused
    # bundle (and name the fact), then serve through lazy builds
    eng3 = BatchDecodeEngine(model, max_slots=2, chunk=4, page_size=16,
                             fused_kernels=False, bundle=path)
    assert eng3._bundle_info["loaded"] is False
    assert "fused" in eng3._bundle_info["error"]
    with pytest.raises(cp.BundleMismatchError, match="fused"):
        eng3.load_serving_bundle(path, strict=True)
    outs3 = _serve(eng3, _reqs())
    for a, b in zip(baseline, outs3):
        assert (a == b).all(), "fallback engine must still be token-exact"
