"""Varlen (packed/unpadded) flash attention vs a per-sequence numpy reference.

Reference surface: flash_attn_unpadded with cu_seqlens
(python/paddle/nn/functional/flash_attention.py:762). The Pallas kernels only
engage on TPU; these tests exercise the XLA segment-mask path, which the TPU
kernels are parity-checked against (same mask semantics, see
ops/kernels/flash_varlen.py).
"""

import numpy as np

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.nn.functional as F

_H, _D = 2, 16
_LENS = [5, 9, 0, 3]  # includes an empty segment
_TOTAL = sum(_LENS)
_PAD = 4


def _data(seed=0):
    rng = np.random.default_rng(seed)
    cu = np.concatenate([[0], np.cumsum(_LENS)]).astype(np.int32)
    mk = lambda: rng.standard_normal((_TOTAL + _PAD, _H, _D)).astype(np.float32)
    return mk(), mk(), mk(), cu


def _ref(q, k, v, cu, causal):
    out = np.zeros_like(q)
    scale = 1.0 / np.sqrt(_D)
    for b in range(len(cu) - 1):
        s, e = cu[b], cu[b + 1]
        if s == e:
            continue
        for hh in range(_H):
            logits = q[s:e, hh] @ k[s:e, hh].T * scale
            if causal:
                logits = np.where(np.tril(np.ones((e - s, e - s), bool)),
                                  logits, -1e30)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[s:e, hh] = p @ v[s:e, hh]
    return out


def test_varlen_forward_matches_reference():
    q, k, v, cu = _data()
    for causal in (False, True):
        out, softmax = F.flash_attn_unpadded(q, k, v, cu, cu, causal=causal)
        assert softmax is None
        o = out.numpy()
        np.testing.assert_allclose(o[:_TOTAL], _ref(q, k, v, cu, causal)[:_TOTAL],
                                   atol=2e-5)
        np.testing.assert_allclose(o[_TOTAL:], 0.0)  # padding rows exactly 0


def test_varlen_no_cross_sequence_leakage():
    """Perturbing sequence b must not change any other sequence's output."""
    q, k, v, cu = _data()
    out0 = F.flash_attn_unpadded(q, k, v, cu, cu, causal=True)[0].numpy()
    k2 = k.copy()
    # random perturbation (a constant shift would cancel in softmax)
    k2[cu[1]:cu[2]] += np.random.default_rng(3).standard_normal(
        k2[cu[1]:cu[2]].shape).astype(np.float32)
    out1 = F.flash_attn_unpadded(q, k2, v, cu, cu, causal=True)[0].numpy()
    np.testing.assert_allclose(out1[:cu[1]], out0[:cu[1]], atol=1e-6)
    np.testing.assert_allclose(out1[cu[2]:_TOTAL], out0[cu[2]:_TOTAL], atol=1e-6)
    assert np.abs(out1[cu[1]:cu[2]] - out0[cu[1]:cu[2]]).max() > 1e-3


def test_varlen_backward_and_numeric_grad():
    q, k, v, cu = _data()
    qt = paddle.to_tensor(q, stop_gradient=False)
    out, _ = F.flash_attn_unpadded(qt, k, v, cu, cu, causal=True)
    out.sum().backward()
    g = qt.grad.numpy()
    assert np.isfinite(g).all()
    np.testing.assert_allclose(g[_TOTAL:], 0.0)  # no grad into padding

    eps = 1e-3
    qp, qm = q.copy(), q.copy()
    qp[2, 0, 3] += eps
    qm[2, 0, 3] -= eps
    num = (_ref(qp, k, v, cu, True)[:_TOTAL].sum()
           - _ref(qm, k, v, cu, True)[:_TOTAL].sum()) / (2 * eps)
    np.testing.assert_allclose(g[2, 0, 3], num, rtol=2e-2)


def test_varlen_cross_lengths():
    """cu_seqlens_q != cu_seqlens_k (e.g. chunked prefill), bottom-right
    causal alignment per segment."""
    rng = np.random.default_rng(1)
    lens_q, lens_k = [4, 6], [7, 9]
    cq = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    ck = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)
    q = rng.standard_normal((cq[-1], _H, _D)).astype(np.float32)
    k = rng.standard_normal((ck[-1], _H, _D)).astype(np.float32)
    v = rng.standard_normal((ck[-1], _H, _D)).astype(np.float32)
    out = F.flash_attn_unpadded(q, k, v, cq, ck, causal=True)[0].numpy()

    scale = 1.0 / np.sqrt(_D)
    for b in range(2):
        qs, qe = cq[b], cq[b + 1]
        ks, ke = ck[b], ck[b + 1]
        Lq, Lk = qe - qs, ke - ks
        for hh in range(_H):
            logits = q[qs:qe, hh] @ k[ks:ke, hh].T * scale
            mask = np.tril(np.ones((Lq, Lk), bool), k=Lk - Lq)
            logits = np.where(mask, logits, -1e30)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            np.testing.assert_allclose(out[qs:qe, hh], p @ v[ks:ke, hh],
                                       atol=2e-5)


def test_flash_attn_unpadded_dropout():
    """dropout routes through the dense path with inverted scaling: mean is
    preserved, ~p of prob mass zeroed, grads flow, and training=False or
    dropout=0 reproduce the exact no-dropout output."""
    import jax.numpy as jnp
    import numpy as np

    from paddlepaddle_tpu.ops.kernels.flash_varlen import flash_attn_unpadded

    rng = np.random.default_rng(0)
    t, h, d = 48, 2, 16
    cu = jnp.asarray([0, 20, 48], jnp.int32)
    q = jnp.asarray(rng.standard_normal((t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((t, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((t, h, d)), jnp.float32)

    base, _ = flash_attn_unpadded(q, k, v, cu, cu, causal=True)
    same, _ = flash_attn_unpadded(q, k, v, cu, cu, causal=True,
                                  dropout=0.5, training=False)
    np.testing.assert_allclose(np.asarray(base.numpy()),
                               np.asarray(same.numpy()), rtol=1e-5, atol=1e-5)

    outs = [flash_attn_unpadded(q, k, v, cu, cu, causal=True, dropout=0.4,
                                fixed_seed_offset=s)[0].numpy()
            for s in (0, 1)]
    assert not np.allclose(outs[0], outs[1])      # different masks
    # deterministic under a fixed seed
    again = flash_attn_unpadded(q, k, v, cu, cu, causal=True, dropout=0.4,
                                fixed_seed_offset=0)[0].numpy()
    np.testing.assert_allclose(outs[0], again)
    # unbiased-ish: averaged over many seeds the mean approaches base
    acc = np.zeros_like(outs[0])
    n = 24
    for s in range(n):
        acc += flash_attn_unpadded(q, k, v, cu, cu, causal=True, dropout=0.4,
                                   fixed_seed_offset=s)[0].numpy()
    err = np.abs(acc / n - base.numpy()).mean()
    assert err < 0.25, err
