"""Native TCPStore, launch CLI, profiler, fft tests."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def test_tcp_store_native_roundtrip():
    from paddlepaddle_tpu.distributed.store import TCPStore

    s = TCPStore(is_master=True)
    c = TCPStore(port=s.port)
    c.set("k", b"v1")
    assert s.get("k") == b"v1"
    assert c.add("cnt", 2) == 2
    assert s.add("cnt", 3) == 5
    assert s.check("k") and not c.check("nope")

    res = {}
    t = threading.Thread(target=lambda: res.update(v=c.get("slow")))
    t.start()
    s.set("slow", b"done")
    t.join(10)
    assert res.get("v") == b"done"


def test_tcp_store_large_value():
    """Values beyond any fixed staging buffer round-trip exactly (the native
    path uses a fetch/copy two-call protocol sized to the actual value)."""
    from paddlepaddle_tpu.distributed.store import TCPStore

    s = TCPStore(is_master=True)
    big = bytes(range(256)) * (5 * 4096)  # 5 MiB
    s.set("big", big)
    assert s.get("big") == big
    s.set("empty", b"")
    assert s.get("empty") == b""

    # concurrent gets on ONE store must not cross-contaminate (the native
    # fetch/copy pair is serialized by a lock)
    s.set("a", b"A" * 100_000)
    s.set("b", b"B" * 50_000)
    results = {}

    def getter(key):
        for _ in range(20):
            results.setdefault(key, set()).add(s.get(key))

    ts = [threading.Thread(target=getter, args=(k,)) for k in ("a", "b") * 2]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert results["a"] == {b"A" * 100_000} and results["b"] == {b"B" * 50_000}


def test_tcp_store_rank_assignment():
    """The reference bootstrap pattern: ranks self-assign via atomic add."""
    from paddlepaddle_tpu.distributed.store import TCPStore

    master = TCPStore(is_master=True)
    ranks = []

    def worker():
        c = TCPStore(port=master.port)
        ranks.append(c.add("next_rank", 1) - 1)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert sorted(ranks) == [0, 1, 2, 3]


def test_launch_cli_runs_script(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import os, sys\n"
        "assert 'PADDLE_TRAINER_ID' in os.environ\n"
        "assert 'MASTER_PORT' in os.environ\n"
        "sys.stdout.write('worker %s of %s\\n' % (os.environ['PADDLE_TRAINER_ID'],\n"
        "                 os.environ['PADDLE_TRAINERS_NUM']))\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run(
        [sys.executable, "-m", "paddlepaddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "worker 0 of 2" in out.stdout
    assert "worker 1 of 2" in out.stdout


def test_launch_restart_on_failure(tmp_path):
    marker = tmp_path / "marker"
    script = tmp_path / "flaky.py"
    script.write_text(
        f"import os, sys\n"
        f"m = {str(marker)!r}\n"
        f"if not os.path.exists(m):\n"
        f"    open(m, 'w').close()\n"
        f"    sys.exit(1)\n"
        f"print('recovered')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run(
        [sys.executable, "-m", "paddlepaddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", "1", str(script)],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "recovered" in out.stdout


def test_record_event_and_summary():
    from paddlepaddle_tpu.profiler import Profiler, RecordEvent

    prof = Profiler(timer_only=True).start()
    with RecordEvent("my_region"):
        _ = paddle.to_tensor(np.ones((4, 4), np.float32)) * 2
    prof.step()
    prof.stop()
    out = prof.summary()
    assert "my_region" in out


def test_make_scheduler():
    from paddlepaddle_tpu.profiler import ProfilerState, make_scheduler

    sched = make_scheduler(closed=1, ready=1, record=2, skip_first=1)
    states = [sched(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED        # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN


def test_fft_roundtrip():
    x = np.random.default_rng(0).standard_normal(16).astype(np.float32)
    X = paddle.fft.fft(paddle.to_tensor(x))
    x2 = paddle.fft.ifft(X)
    np.testing.assert_allclose(np.asarray(x2.numpy()).real, x, atol=1e-5)
    np.testing.assert_allclose(np.asarray(X.numpy()),
                               np.fft.fft(x), rtol=1e-4, atol=1e-4)
    r = paddle.fft.rfft(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(r.numpy()), np.fft.rfft(x), rtol=1e-4, atol=1e-4)
