"""jit.save/load (StableHLO), inference Predictor, sparse, static shim,
incubate fused ops, auto_parallel parallelize/to_static."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def test_jit_save_load_roundtrip(tmp_path):
    from paddlepaddle_tpu.static import InputSpec

    m = paddle.nn.Linear(4, 3)
    x = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    ref = m(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(m, path, input_spec=[InputSpec([2, 4], "float32")])
    loaded = paddle.jit.load(path)
    out = loaded(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_inference_predictor(tmp_path):
    from paddlepaddle_tpu.inference import Config, create_predictor
    from paddlepaddle_tpu.static import InputSpec

    m = paddle.nn.Linear(4, 2)
    x = np.random.default_rng(1).standard_normal((3, 4)).astype(np.float32)
    ref = m(x).numpy()
    path = str(tmp_path / "deploy")
    paddle.jit.save(m, path, input_spec=[InputSpec([3, 4], "float32")])
    pred = create_predictor(Config(path))
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_sparse_coo():
    idx = np.array([[0, 1, 2], [1, 2, 0]])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    s = paddle.sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
    dense = s.to_dense().numpy()
    assert dense[0, 1] == 1.0 and dense[1, 2] == 2.0 and dense[2, 0] == 3.0
    y = np.eye(3, dtype=np.float32)
    out = paddle.sparse.matmul(s, y)
    np.testing.assert_allclose(out.numpy(), dense @ y)
    r = paddle.sparse.relu(paddle.sparse.sparse_coo_tensor(idx, -vals, shape=[3, 3]))
    assert r.to_dense().numpy().sum() == 0.0


def test_static_shim():
    import paddlepaddle_tpu.static as static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4])
        assert x.shape[1] == 4
    exe = static.Executor()
    prog._fn = lambda x: paddle.to_tensor(np.asarray(x) * 2)
    (out,) = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[x])
    np.testing.assert_allclose(out, 2 * np.ones((2, 4)))


def test_incubate_fused_ops():
    from paddlepaddle_tpu.incubate.nn import functional as IF

    x = np.random.default_rng(0).standard_normal((2, 4, 8)).astype(np.float32)
    w = np.ones((8,), np.float32)
    out = IF.fused_rms_norm(paddle.to_tensor(x), paddle.to_tensor(w))
    ref = paddle.nn.functional.rms_norm(paddle.to_tensor(x), paddle.to_tensor(w))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)

    q = np.random.default_rng(1).standard_normal((1, 4, 2, 8)).astype(np.float32)
    cos = np.cos(np.outer(np.arange(4), np.ones(8))).astype(np.float32)
    sin = np.sin(np.outer(np.arange(4), np.ones(8))).astype(np.float32)
    qo, ko, vo = IF.fused_rotary_position_embedding(
        paddle.to_tensor(q), sin=paddle.to_tensor(sin), cos=paddle.to_tensor(cos))
    assert qo.shape == [1, 4, 2, 8] and ko is None


def test_incubate_autograd():
    from paddlepaddle_tpu.incubate.autograd import hessian, jacobian

    x = np.array([1.0, 2.0], np.float32)
    jac = jacobian(lambda t: (t * t).sum(), paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(jac.numpy()), [2.0, 4.0], rtol=1e-5)
    h = hessian(lambda t: (t ** 3).sum(), paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(h.numpy()), np.diag([6.0, 12.0]), rtol=1e-5)


def test_parallelize_plans():
    from paddlepaddle_tpu.distributed import ColWiseParallel, RowWiseParallel, parallelize

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.up = paddle.nn.Linear(8, 16)
            self.down = paddle.nn.Linear(16, 8)

        def forward(self, x):
            return self.down(self.up(x))

    net = Net()
    parallelize(net, config={"mp_config": {"parallelize_plan": {
        "up": ColWiseParallel(), "down": RowWiseParallel()}}})
    assert net.up.weight.dist_spec == (None, "mp")
    assert net.up.bias.dist_spec == ("mp",)
    assert net.down.weight.dist_spec == ("mp", None)
    with pytest.raises(ValueError):
        parallelize(net, config={"mp_config": {"parallelize_plan": {
            "nonexistent_layer_xyz": ColWiseParallel()}}})


def test_dist_to_static():
    import jax

    from paddlepaddle_tpu.distributed import to_static
    from paddlepaddle_tpu.distributed.mesh import ProcessMesh, set_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = ProcessMesh(shape=[4, 2], dim_names=["dp", "mp"])
    set_mesh(mesh)

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 8)

        def forward(self, x):
            return self.fc(x)

    net = Net()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=net.parameters())
    dist_model = to_static(net, loss=paddle.nn.functional.mse_loss, optimizer=opt,
                           mesh=mesh, rules=[(r".*", ())])
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8)).astype(np.float32)
    y = rng.standard_normal((8, 8)).astype(np.float32)
    losses = [float(dist_model(x, y).numpy()) for _ in range(5)]
    assert losses[-1] < losses[0]
    dist_model.eval()
    ev = float(dist_model(x, y).numpy())
    assert np.isfinite(ev)
    set_mesh(None)
