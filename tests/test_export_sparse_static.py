"""jit.save/load (StableHLO), inference Predictor, sparse, static shim,
incubate fused ops, auto_parallel parallelize/to_static."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def test_jit_save_load_roundtrip(tmp_path):
    from paddlepaddle_tpu.static import InputSpec

    m = paddle.nn.Linear(4, 3)
    x = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    ref = m(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(m, path, input_spec=[InputSpec([2, 4], "float32")])
    loaded = paddle.jit.load(path)
    out = loaded(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_inference_predictor(tmp_path):
    from paddlepaddle_tpu.inference import Config, create_predictor
    from paddlepaddle_tpu.static import InputSpec

    m = paddle.nn.Linear(4, 2)
    x = np.random.default_rng(1).standard_normal((3, 4)).astype(np.float32)
    ref = m(x).numpy()
    path = str(tmp_path / "deploy")
    paddle.jit.save(m, path, input_spec=[InputSpec([3, 4], "float32")])
    pred = create_predictor(Config(path))
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_sparse_coo():
    idx = np.array([[0, 1, 2], [1, 2, 0]])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    s = paddle.sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
    dense = s.to_dense().numpy()
    assert dense[0, 1] == 1.0 and dense[1, 2] == 2.0 and dense[2, 0] == 3.0
    y = np.eye(3, dtype=np.float32)
    out = paddle.sparse.matmul(s, y)
    np.testing.assert_allclose(out.numpy(), dense @ y)
    r = paddle.sparse.relu(paddle.sparse.sparse_coo_tensor(idx, -vals, shape=[3, 3]))
    assert r.to_dense().numpy().sum() == 0.0


def test_static_shim():
    import paddlepaddle_tpu.static as static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4])
        assert x.shape[1] == 4
    exe = static.Executor()
    prog._fn = lambda x: paddle.to_tensor(np.asarray(x) * 2)
    (out,) = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[x])
    np.testing.assert_allclose(out, 2 * np.ones((2, 4)))


def test_incubate_fused_ops():
    from paddlepaddle_tpu.incubate.nn import functional as IF

    x = np.random.default_rng(0).standard_normal((2, 4, 8)).astype(np.float32)
    w = np.ones((8,), np.float32)
    out = IF.fused_rms_norm(paddle.to_tensor(x), paddle.to_tensor(w))
    ref = paddle.nn.functional.rms_norm(paddle.to_tensor(x), paddle.to_tensor(w))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)

    q = np.random.default_rng(1).standard_normal((1, 4, 2, 8)).astype(np.float32)
    cos = np.cos(np.outer(np.arange(4), np.ones(8))).astype(np.float32)
    sin = np.sin(np.outer(np.arange(4), np.ones(8))).astype(np.float32)
    qo, ko, vo = IF.fused_rotary_position_embedding(
        paddle.to_tensor(q), sin=paddle.to_tensor(sin), cos=paddle.to_tensor(cos))
    assert qo.shape == [1, 4, 2, 8] and ko is None


def test_incubate_autograd():
    from paddlepaddle_tpu.incubate.autograd import hessian, jacobian

    x = np.array([1.0, 2.0], np.float32)
    jac = jacobian(lambda t: (t * t).sum(), paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(jac.numpy()), [2.0, 4.0], rtol=1e-5)
    h = hessian(lambda t: (t ** 3).sum(), paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(h.numpy()), np.diag([6.0, 12.0]), rtol=1e-5)


def test_parallelize_plans():
    from paddlepaddle_tpu.distributed import ColWiseParallel, RowWiseParallel, parallelize

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.up = paddle.nn.Linear(8, 16)
            self.down = paddle.nn.Linear(16, 8)

        def forward(self, x):
            return self.down(self.up(x))

    net = Net()
    parallelize(net, config={"mp_config": {"parallelize_plan": {
        "up": ColWiseParallel(), "down": RowWiseParallel()}}})
    assert net.up.weight.dist_spec == (None, "mp")
    assert net.up.bias.dist_spec == ("mp",)
    assert net.down.weight.dist_spec == ("mp", None)
    with pytest.raises(ValueError):
        parallelize(net, config={"mp_config": {"parallelize_plan": {
            "nonexistent_layer_xyz": ColWiseParallel()}}})


def test_dist_to_static():
    import jax

    from paddlepaddle_tpu.distributed import to_static
    from paddlepaddle_tpu.distributed.mesh import ProcessMesh, set_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = ProcessMesh(shape=[4, 2], dim_names=["dp", "mp"])
    set_mesh(mesh)

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 8)

        def forward(self, x):
            return self.fc(x)

    net = Net()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=net.parameters())
    dist_model = to_static(net, loss=paddle.nn.functional.mse_loss, optimizer=opt,
                           mesh=mesh, rules=[(r".*", ())])
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8)).astype(np.float32)
    y = rng.standard_normal((8, 8)).astype(np.float32)
    losses = [float(dist_model(x, y).numpy()) for _ in range(5)]
    assert losses[-1] < losses[0]
    dist_model.eval()
    ev = float(dist_model(x, y).numpy())
    assert np.isfinite(ev)
    set_mesh(None)


def test_static_executor_runs_reference_example():
    """The reference's canonical static workflow runs UNCHANGED
    (executor.py:1247 feed/fetch contract + minimize): build under
    enable_static, run startup, then exe.run(feed=..., fetch_list=[loss])
    trains to convergence by replaying the recorded op tape."""
    import numpy as np

    import paddlepaddle_tpu as paddle

    paddle.enable_static()
    try:
        prog = paddle.static.Program()
        with paddle.static.program_guard(prog):
            x = paddle.static.data(name='x', shape=[None, 4], dtype='float32')
            y = paddle.static.data(name='y', shape=[None, 1], dtype='float32')
            pred = paddle.static.nn.fc(x, size=1)
            loss = ((pred - y) ** 2).mean()
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = paddle.static.Executor()
        exe.run(paddle.static.default_startup_program())
        rng = np.random.default_rng(0)
        w_true = np.asarray([[1.], [2.], [-1.], [0.5]], np.float32)
        losses = []
        for _ in range(25):
            xb = rng.standard_normal((16, 4)).astype(np.float32)
            out, = exe.run(prog, feed={'x': xb, 'y': xb @ w_true},
                           fetch_list=[loss])
            losses.append(float(out))
        assert losses[-1] < losses[0] * 0.2, losses[::6]
        # fetch without minimize side-effects: same program, eval fetch
        out2, = exe.run(prog, feed={'x': np.ones((3, 4), np.float32),
                                    'y': np.zeros((3, 1), np.float32)},
                        fetch_list=[pred])
        assert out2.shape == (3, 1)
    finally:
        paddle.disable_static()


def test_static_mode_flags():
    import paddlepaddle_tpu as paddle

    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        assert not paddle.in_dynamic_mode()
    finally:
        paddle.disable_static()
    assert paddle.in_dynamic_mode()


def test_static_executor_int_labels_and_feed_errors():
    """Int (non-differentiable) placeholders must be fed through replay too
    — the autograd tape alone would bake them as build-time zeros — and the
    feed contract raises on unknown names and un-fed placeholders."""
    import numpy as np
    import pytest

    import paddlepaddle_tpu as paddle

    paddle.enable_static()
    try:
        prog = paddle.static.Program()
        with paddle.static.program_guard(prog):
            x = paddle.static.data(name='x', shape=[None, 4], dtype='float32')
            lbl = paddle.static.data(name='lbl', shape=[None], dtype='int64')
            logits = paddle.static.nn.fc(x, size=3)
            loss = paddle.nn.functional.cross_entropy(logits, lbl).mean()
        exe = paddle.static.Executor()
        rng = np.random.default_rng(0)
        xb = rng.standard_normal((8, 4)).astype(np.float32)
        y0 = np.zeros((8,), np.int64)
        y2 = np.full((8,), 2, np.int64)
        l0, = exe.run(prog, feed={'x': xb, 'lbl': y0}, fetch_list=[loss])
        l2, = exe.run(prog, feed={'x': xb, 'lbl': y2}, fetch_list=[loss])
        assert abs(float(l0) - float(l2)) > 1e-6, (
            "labels fed through replay must change the loss")
        with pytest.raises(KeyError, match="no static.data placeholder"):
            exe.run(prog, feed={'X_typo': xb, 'lbl': y0}, fetch_list=[loss])
        with pytest.raises(KeyError, match="was not fed"):
            exe.run(prog, feed={'x': xb}, fetch_list=[loss])
    finally:
        paddle.disable_static()


def test_static_fc_flatten_dims_batch_polymorphic():
    import numpy as np

    import paddlepaddle_tpu as paddle

    paddle.enable_static()
    try:
        prog = paddle.static.Program()
        with paddle.static.program_guard(prog):
            x = paddle.static.data(name='x', shape=[None, 2, 3, 4],
                                   dtype='float32')
            out = paddle.static.nn.fc(x, size=5, num_flatten_dims=2)
        exe = paddle.static.Executor()
        xb = np.random.default_rng(0).standard_normal((7, 2, 3, 4)).astype(
            np.float32)
        o, = exe.run(prog, feed={'x': xb}, fetch_list=[out])
        assert o.shape == (7, 2, 5)
    finally:
        paddle.disable_static()
