"""KV memory at scale (ROADMAP item 4): int8 KV pages + host-RAM
prefix-cache tier.

The acceptance surface: the page-slab wire format round-trips
byte-exactly (the same framing the disaggregated-prefill seam will
speak), the host tier's LRU/budget bookkeeping is exact, the quantized
paged-attention kernel is token-exact against the gather-dequant
reference at W=1 AND the speculative verify width (identical quantized
bytes in, identical tokens out), int8 KV holds greedy top-1 agreement
against full-precision KV, a spilled-then-restored prefix hit emits the
same tokens as one that never left the device, a corrupted slab degrades
to a full-prefill miss (never a wrong token), and the chaos drill leaks
zero pages on either tier."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.inference.decode_engine import BatchDecodeEngine
from paddlepaddle_tpu.inference.kv_pool import (
    HostPrefixTier,
    HostSlab,
    deserialize_page_slab,
    prefix_hash,
    serialize_page_slab,
)
from paddlepaddle_tpu.inference.serving import GenerationRequest


def _model(dtype="float32"):
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=192,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=96, dtype=dtype))


def _req(ids, n, temp=0.0, top_k=0, eos=None, prefix_len=None):
    r = GenerationRequest(ids, n, temp, top_k, eos)
    r.prefix_len = prefix_len
    return r


def _serve(eng, reqs, timeout=240):
    eng.serve(reqs, timeout=timeout)
    return [np.asarray(r.result.result(5)) for r in reqs]


def _prompts(seed=0, lens=(12, 20, 7)):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 127, size=(1, n)) for n in lens]


# -- page-slab wire format ----------------------------------------------------

def test_slab_roundtrip_byte_exact():
    rng = np.random.default_rng(3)
    arrays = [
        rng.standard_normal((4, 8, 2, 16)).astype(np.float32),
        rng.integers(-127, 128, (4, 8, 2, 16)).astype(np.int8),
        rng.standard_normal((4, 2)).astype(np.float32),
    ]
    meta = {"page_size": 8, "kv_quant": "int8", "length": 30}
    blob = serialize_page_slab(meta, arrays)
    m2, arrs2 = deserialize_page_slab(blob)
    assert m2 == meta
    assert len(arrs2) == len(arrays)
    for a, b in zip(arrays, arrs2):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_slab_roundtrip_bfloat16():
    # the serving dtype: bf16's numpy .str is an anonymous void — the
    # format must carry the NAME so the reader reconstructs the real type
    ml_dtypes = pytest.importorskip("ml_dtypes")
    x = np.arange(32, dtype=np.float32).astype(ml_dtypes.bfloat16)
    blob = serialize_page_slab({"dtype": "bfloat16"}, [x])
    _, (y,) = deserialize_page_slab(blob)
    assert y.dtype == x.dtype and y.tobytes() == x.tobytes()


def test_slab_rejects_corruption():
    blob = serialize_page_slab({"k": 1}, [np.zeros(4, np.float32)])
    with pytest.raises(ValueError):
        deserialize_page_slab(b"XXXX" + blob[4:])      # bad magic
    with pytest.raises(ValueError):
        deserialize_page_slab(blob[:-3])               # truncated payload
    with pytest.raises(ValueError):
        deserialize_page_slab(blob + b"\x00")          # trailing bytes


# -- host tier bookkeeping ----------------------------------------------------

def _slab(nbytes, stamp):
    return HostSlab(b"x" * nbytes, length=8, n_pages=1, stamp=stamp)


def test_host_tier_lru_budget_and_oversize():
    with pytest.raises(ValueError):
        HostPrefixTier(0)
    tier = HostPrefixTier(100)
    assert tier.put("a", _slab(40, stamp=1.0))
    assert tier.put("b", _slab(40, stamp=2.0))
    # over budget: oldest-stamp entry ("a") is the discard victim
    assert tier.put("c", _slab(40, stamp=3.0))
    assert tier.pop("a") is None and tier.discards == 1
    assert sorted(tier.keys()) == ["b", "c"]
    assert tier.used_bytes == 80
    # a slab larger than the whole budget is refused, not thrashed in
    assert not tier.put("big", _slab(200, stamp=4.0))
    assert tier.discards == 2 and sorted(tier.keys()) == ["b", "c"]
    # pop decrements, put_back restores without double-counting stats
    s = tier.pop("b")
    assert s is not None and tier.used_bytes == 40 and tier.restores == 1
    tier.put_back("b", s)
    assert tier.used_bytes == 80 and tier.restores == 0
    st = tier.stats()
    assert st["entries"] == 2 and st["budget_bytes"] == 100
    assert st["occupancy"] == pytest.approx(0.8)


# -- int8 kernel vs gather-dequant reference ----------------------------------

@pytest.mark.parametrize("W", [1, 3])
def test_int8_kernel_matches_dequant_reference(W):
    """Token-exact contract at identical quantized bytes: the in-VMEM
    dequant (codes * scale inside the kernel) must equal running the SAME
    kernel over pre-dequantized f32 pools — W=1 is the chunked decode
    step, W=3 the speculative verify width."""
    import jax.numpy as jnp

    from paddlepaddle_tpu.ops.kernels.paged_attention import paged_attention

    rng = np.random.default_rng(7)
    S, h, kvh, hd, ps, P = 2, 4, 2, 16, 8, 3
    npages = S * P + 1
    q = rng.standard_normal((S, W, h, hd)).astype(np.float32)
    kq = rng.integers(-127, 128, (npages, ps, kvh, hd)).astype(np.int8)
    vq = rng.integers(-127, 128, (npages, ps, kvh, hd)).astype(np.int8)
    ks = rng.uniform(0.001, 0.02, (npages, kvh)).astype(np.float32)
    vs = rng.uniform(0.001, 0.02, (npages, kvh)).astype(np.float32)
    pt = np.arange(1, npages, dtype=np.int32).reshape(S, P)
    lens = np.array([11, ps * P - W], dtype=np.int32)
    kw = dict(rep=h // kvh, scale=hd ** -0.5, interpret=True)
    out_q = paged_attention(jnp.asarray(q), jnp.asarray(kq),
                            jnp.asarray(vq), pt, lens,
                            k_scale=ks, v_scale=vs, **kw)
    kd = kq.astype(np.float32) * ks[:, None, :, None]
    vd = vq.astype(np.float32) * vs[:, None, :, None]
    out_f = paged_attention(jnp.asarray(q), jnp.asarray(kd),
                            jnp.asarray(vd), pt, lens, **kw)
    np.testing.assert_array_equal(np.asarray(out_q), np.asarray(out_f))


# -- engine-level parity ------------------------------------------------------

def test_engine_int8_fused_vs_reference_token_exact():
    prompts = _prompts()

    def run(fused):
        eng = BatchDecodeEngine(_model(), max_slots=4, chunk=4, page_size=8,
                                kv_quant="int8", fused_kernels=fused)
        if fused:
            assert eng.fused.get("enabled"), eng.fused
        return _serve(eng, [_req(p, 8) for p in prompts])

    for a, b in zip(run(False), run(True)):
        np.testing.assert_array_equal(a, b)


def test_engine_int8_greedy_agreement_vs_full_precision():
    prompts = _prompts(seed=1)

    def run(**kw):
        eng = BatchDecodeEngine(_model(), max_slots=4, chunk=4,
                                page_size=8, **kw)
        return _serve(eng, [_req(p, 8) for p in prompts])

    base = run()
    quant = run(kv_quant="int8", fused_kernels=True)
    agree = np.mean([np.mean(a[p.shape[1]:] == b[p.shape[1]:])
                     for a, b, p in zip(base, quant, prompts)])
    assert agree >= 0.9, f"greedy top-1 agreement {agree} < 0.9"


def test_kv_quant_validation_and_fingerprint():
    from paddlepaddle_tpu.inference import compile_plan as cp

    m = _model()
    with pytest.raises(ValueError, match="int4.*seam"):
        BatchDecodeEngine(m, max_slots=2, kv_quant="int4")
    with pytest.raises(ValueError):
        BatchDecodeEngine(m, max_slots=2, kv_quant="int3")
    with pytest.raises(ValueError, match="paged"):
        BatchDecodeEngine(m, max_slots=2, kv_layout="contiguous",
                          kv_quant="int8")
    # kv_quant changes every decode program AND the cache treedef — it
    # must be a compile-plan fact or an AOT bundle would cross-load
    base = BatchDecodeEngine(m, max_slots=2, chunk=4, page_size=8)
    quant = BatchDecodeEngine(m, max_slots=2, chunk=4, page_size=8,
                              kv_quant="int8")
    assert cp.CompilePlan.for_engine(base).fingerprint() \
        != cp.CompilePlan.for_engine(quant).fingerprint()
    assert base.kv_stats()["kv_quant"] == "off"
    assert quant.kv_stats()["kv_quant"] == "int8"
    # int8 pages are smaller than f32 pages at the same page_size
    assert quant.kv_stats()["page_bytes"] < base.kv_stats()["page_bytes"]


# -- tiered prefix cache ------------------------------------------------------

def _tiered_engine(num_pages=6, host_bytes=1 << 20, **kw):
    return BatchDecodeEngine(_model(), max_slots=1, chunk=4, page_size=8,
                             kv_quant="int8", fused_kernels=True,
                             prefix_cache=True, num_pages=num_pages,
                             kv_host_bytes=host_bytes, **kw)


def _prefix_reqs(seed=1):
    rng = np.random.default_rng(seed)
    pfx_a = rng.integers(1, 127, size=(1, 16))
    pfx_b = rng.integers(1, 127, size=(1, 16))
    tail = rng.integers(1, 127, size=(1, 4))
    mk = lambda p: _req(np.concatenate([p, tail], 1), 6, prefix_len=16)
    return pfx_a, pfx_b, mk


def test_spill_restore_token_parity():
    """A prefix evicted to the host tier and restored on re-hit must emit
    EXACTLY the tokens of (a) its own first run and (b) a pool big enough
    that it never left the device — the restore path re-materializes the
    same quantized bytes, so parity is byte-level, not approximate."""
    pfx_a, pfx_b, mk = _prefix_reqs()
    eng = _tiered_engine()                 # 5 usable pages: B evicts A
    a1 = _serve(eng, [mk(pfx_a)])
    _serve(eng, [mk(pfx_b)])
    st = eng.kv_host.stats()
    assert st["spills"] >= 1 and st["entries"] >= 1
    a2 = _serve(eng, [mk(pfx_a)])
    st = eng.kv_host.stats()
    assert st["restores"] >= 1
    np.testing.assert_array_equal(a1[0], a2[0])
    ks = eng.kv_stats()
    assert ks["host"]["enabled"]
    assert ks["host"]["restore_ms_p50"] is not None
    assert ks["host"]["restore_ms_p99"] >= ks["host"]["restore_ms_p50"]
    # never-evicted control: same prompts, pool big enough to keep A
    big = _tiered_engine(num_pages=32)
    _serve(big, [mk(pfx_a)])
    c2 = _serve(big, [mk(pfx_a)])
    assert big.kv_host.stats()["spills"] == 0
    np.testing.assert_array_equal(a2[0], c2[0])


def test_corrupt_slab_degrades_to_miss():
    pfx_a, pfx_b, mk = _prefix_reqs()
    eng = _tiered_engine()
    a1 = _serve(eng, [mk(pfx_a)])
    _serve(eng, [mk(pfx_b)])               # spills A's slab to host
    h = prefix_hash(pfx_a, 16)
    slab = eng.kv_host.pop(h)
    assert slab is not None
    # a slab whose meta doesn't match the engine (wrong page geometry,
    # different quant mode, foreign model) must be a loud miss — the
    # request full-prefills and still finishes with the right tokens
    bad = serialize_page_slab({"garbage": True}, [np.zeros(4, np.int8)])
    eng.kv_host.put_back(h, HostSlab(bad, slab.length, slab.n_pages,
                                     slab.stamp))
    a2 = _serve(eng, [mk(pfx_a)])
    np.testing.assert_array_equal(a1[0], a2[0])
    assert eng.prefix.misses >= 1


def test_host_tier_off_is_plain_eviction():
    pfx_a, pfx_b, mk = _prefix_reqs()
    eng = BatchDecodeEngine(_model(), max_slots=1, chunk=4, page_size=8,
                            prefix_cache=True, num_pages=6)
    assert eng.kv_host is None
    a1 = _serve(eng, [mk(pfx_a)])
    _serve(eng, [mk(pfx_b)])
    assert eng.prefix.evictions >= 1       # true discard, no tier to catch
    a2 = _serve(eng, [mk(pfx_a)])
    np.testing.assert_array_equal(a1[0], a2[0])


# -- observability ------------------------------------------------------------

def test_memledger_host_bucket_and_cross_tier_leak_check():
    from paddlepaddle_tpu.observability import memledger

    assert "kv_host_spill" in memledger.BUCKETS
    pfx_a, pfx_b, mk = _prefix_reqs()
    eng = _tiered_engine()
    _serve(eng, [mk(pfx_a)])
    _serve(eng, [mk(pfx_b)])               # A now lives on the host tier
    lc = memledger.leak_check(eng)
    assert lc["leaked_pages"] == 0
    assert lc["host_entries"] >= 1
    assert lc["host_bytes"] == eng.kv_host.used_bytes > 0
    assert lc["tier_overlap"] == 0         # device XOR host, never both
    led = memledger.MemoryLedger()
    sample = led.sample()
    assert sample["buckets"]["kv_host_spill"] >= eng.kv_host.used_bytes
    # host RAM must NOT be folded into the device-bytes reconciliation:
    # unattributed reconciles live DEVICE arrays against the device
    # buckets only, so it is exactly live - (params+kv+pinned+draft)
    attributed_device = (sample["buckets"]["params"]
                         + sample["buckets"]["kv_pages"]
                         + sample["buckets"]["prefix_pinned"]
                         + sample["buckets"]["draft"])
    assert sample["buckets"]["unattributed"] == max(
        sample["live_array_bytes"] - attributed_device, 0)


def test_alert_rule_kv_host_tier_full():
    from paddlepaddle_tpu.observability.alerts import default_rules

    rules = {r.name: r for r in default_rules()}
    rule = rules["kv_host_tier_full"]
    assert rule.severity == "warn"
    assert any(c.series == "paddle_serving_kv_host_occupancy"
               for c in rule.conditions)


def test_perf_gate_kv_memory_fields():
    import sys

    sys.path.insert(0, "tools")
    try:
        import perf_gate
    finally:
        sys.path.pop(0)
    body = {"aggregate_tok_s": 100.0, "prefix_restore_ms_p50": 3.0,
            "prefix_restore_ms_p99": 9.0,
            "kv_quant_ab": {"int8": {"aggregate_tok_s": 90.0,
                                     "concurrency_peak": 8}}}
    m = perf_gate.serving_metrics({"serving_bench": body})
    assert m["serving.prefix_restore_ms_p50"] == (3.0, perf_gate.LOWER)
    assert m["serving.prefix_restore_ms_p99"] == (9.0, perf_gate.LOWER)
    assert m["serving.kvq_mixed_tok_s"] == (90.0, perf_gate.HIGHER)
    assert m["serving.kvq_concurrency_peak"] == (8.0, perf_gate.HIGHER)


# -- chaos drill: zero leaked pages on either tier ----------------------------

@pytest.mark.chaos
def test_chaos_tiered_kv_zero_leak_both_tiers():
    """Churn a deliberately tiny two-tier config — spills, restores, host
    discards, failed restores all fire — then audit: every device page is
    owned by a slot or the prefix cache, no prefix hash is resident on
    both tiers, and the host tier's byte ledger matches its entries."""
    from paddlepaddle_tpu.observability import memledger

    rng = np.random.default_rng(11)
    prefixes = [rng.integers(1, 127, size=(1, 16)) for _ in range(4)]
    tail = rng.integers(1, 127, size=(1, 4))
    # host budget fits ONE ~2.6KB slab: concurrent spills force true
    # host-tier discards alongside the restores
    eng = _tiered_engine(num_pages=6, host_bytes=3000)
    order = rng.permutation(np.repeat(np.arange(4), 3))
    for i in order:
        _serve(eng, [_req(np.concatenate([prefixes[i], tail], 1), 4,
                          prefix_len=16)])
    st = eng.kv_host.stats()
    assert st["spills"] >= 3 and st["discards"] >= 1
    lc = memledger.leak_check(eng)
    assert lc["leaked_pages"] == 0, lc
    assert lc["tier_overlap"] == 0, lc
    # the host byte ledger must equal the sum of the resident slabs, and
    # popping every entry must drain it to exactly zero
    resident = sum(eng.kv_host.pop(h).nbytes
                   for h in list(eng.kv_host.keys()))
    assert lc["host_bytes"] == resident
    assert eng.kv_host.used_bytes == 0
