"""History & alerting plane (observability/tsdb.py + alerts.py, the
exporter ``/query``//``/alerts`` routes, the TCPStore ``/fleet/query``
merge, and the obsctl ``query``/``alerts``/``top`` surfaces).

The acceptance surface of ISSUE 16: bounded per-series rings sampled by
diffing registry snapshots (counters as rates, gauges as values,
histograms as per-window quantile estimates), two-tier downsampling that
cannot hide spikes, multi-window burn-rate alert rules with hold-down,
exactly one flight dump per firing episode carrying the slowest request
journeys, and the fleet-wide query path over a real two-rank TCPStore.

Unit tests drive ``MetricHistory.observe(now=...)`` with a synthetic
clock against private registries — no threads, no sleeps. The
latency-storm acceptance drill (default ruleset fires under a chaos
``serving.decode`` latency injection against a 2-replica fleet) is
``chaos``-marked and runs via tools/run_chaos.sh.
"""

import importlib.util
import json
import os
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.observability as obs
from paddlepaddle_tpu.core import flags as _flags
from paddlepaddle_tpu.observability import (
    aggregate,
    alerts,
    exporter,
    flight,
    reqtrace,
    tsdb,
)
from paddlepaddle_tpu.observability.metrics import Registry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBSCTL = os.path.join(_REPO, "tools", "obsctl.py")


@pytest.fixture
def clean_hist():
    """Every singleton this plane touches, reset before AND after:
    registry/recorder, history sampler + alert engine, flight recorder,
    reqtrace, exporter."""
    obs.disable()
    obs.reset()
    flight.disable()
    exporter.stop()
    yield obs
    obs.disable()
    obs.reset()
    flight.disable()
    exporter.stop()


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# SeriesRing: bounds, downsampling, tier-aware window aggregation
# ---------------------------------------------------------------------------

def test_series_ring_bounds_and_downsample():
    ring = tsdb.SeriesRing("gauge", capacity=8)
    for i in range(25):
        ring.append(float(i), float(i))
    assert len(ring.raw) == 8                        # bounded
    assert [p[1] for p in ring.raw] == [17.0, 18.0, 19.0, 20.0, 21.0,
                                        22.0, 23.0, 24.0]
    # every DOWNSAMPLE raw appends collapse to one (t, mean, min, max)
    assert len(ring.coarse) == 25 // tsdb.DOWNSAMPLE
    t, mean, lo, hi = ring.coarse[0]
    assert (t, mean, lo, hi) == (9.0, 4.5, 0.0, 9.0)


def test_window_agg_coarse_tier_keeps_spikes():
    """A spike the raw ring has already forgotten must survive in the
    coarse tier's per-point extrema — downsampling cannot hide it."""
    ring = tsdb.SeriesRing("gauge", capacity=4)
    for i in range(20):
        ring.append(float(i), 100.0 if i == 3 else 1.0)
    # the spike at t=3 fell off the 4-point raw ring long ago
    assert all(v == 1.0 for _, v in ring.raw)
    tier, _pts = ring.points(window_s=100.0, now=19.0)
    assert tier == "coarse"
    assert ring.window_agg(100.0, "max", now=19.0) == 100.0
    assert ring.window_agg(100.0, "min", now=19.0) == 1.0
    # a window the raw ring still covers answers from raw
    tier, pts = ring.points(window_s=3.0, now=19.0)
    assert tier == "raw" and all(p[0] >= 16.0 for p in pts)
    # no points in the window -> None, never a fake zero
    assert ring.window_agg(0.5, "avg", now=500.0) is None


def test_match_series_selector_semantics():
    ids = ['a_total{op="add"}', 'a_total{op="mul"}', "b_gauge",
           'a_total:p99{op="add"}']
    assert tsdb.match_series(ids, None) == sorted(ids)
    assert tsdb.match_series(ids, "a_total") == [
        'a_total{op="add"}', 'a_total{op="mul"}']
    assert tsdb.match_series(ids, "a_total:p99") == ['a_total:p99{op="add"}']
    assert tsdb.match_series(ids, 'a_total{op="mul"}') == [
        'a_total{op="mul"}']
    assert tsdb.match_series(ids, "a_*") == [
        'a_total:p99{op="add"}', 'a_total{op="add"}', 'a_total{op="mul"}']
    assert tsdb.match_series(ids, "nope") == []


# ---------------------------------------------------------------------------
# MetricHistory: counters->rates, gauges->values, histogram quantiles
# ---------------------------------------------------------------------------

def test_counter_sampled_as_rate_and_reset_dropped():
    reg = Registry()
    c = reg.counter("paddle_t_total", "probe")
    g = reg.gauge("paddle_t_gauge", "probe")
    h = tsdb.MetricHistory(reg, interval_s=1.0, capacity=32)

    c.inc(5)
    g.set(7.0)
    h.observe(now=1000.0)          # first pass primes the counter diff
    c.inc(10)
    g.set(9.0)
    h.observe(now=1002.0)
    rates = h.window_agg("paddle_t_total", 60.0, "last", now=1002.0)
    (rate,) = rates.values()
    assert rate == pytest.approx(5.0)      # 10 over 2s
    # gauges are sampled values from the very first pass
    doc = h.query("paddle_t_gauge", now=1002.0)
    (row,) = doc["series"]
    assert row["kind"] == "gauge"
    assert [p[1] for p in row["points"]] == [7.0, 9.0]

    # counter reset (restart / clear): negative delta is DROPPED, not a
    # huge negative rate; the next interval diffs against the new base
    reg.clear()
    reg.counter("paddle_t_total", "probe").inc(1)
    before = len(h.query("paddle_t_total", now=1002.0)["series"][0]["points"])
    h.observe(now=1004.0)
    after_doc = h.query("paddle_t_total", now=1004.0)["series"][0]
    assert len(after_doc["points"]) == before          # dropped interval
    reg.get("paddle_t_total").inc(4)
    h.observe(now=1006.0)
    assert h.window_agg("paddle_t_total", 60.0, "last",
                        now=1006.0)[after_doc["id"]] == pytest.approx(2.0)
    assert all(p[1] >= 0 for p in
               h.query("paddle_t_total", now=1006.0)["series"][0]["points"])


def test_histogram_window_quantiles_and_gaps():
    reg = Registry()
    hist = reg.histogram("paddle_t_seconds", "probe")
    # an empty histogram has no snapshot entry, so the priming pass needs
    # at least one observation to diff against
    hist.observe(0.5)
    h = tsdb.MetricHistory(reg, interval_s=1.0, capacity=32)
    h.observe(now=0.0)             # prime
    for v in [0.001] * 90 + [0.9] * 10:
        hist.observe(v)
    h.observe(now=2.0)
    ids = h.series_ids()
    assert any(":p50" in s for s in ids)
    assert any(":p99" in s for s in ids)
    assert any(":rate" in s for s in ids)
    assert any(":mean" in s for s in ids)

    def last(sel):
        vals = h.window_agg(sel, 60.0, "last", now=2.0)
        (v,) = vals.values()
        return v

    # window quantile estimates report bucket UPPER bounds (le-semantics):
    # conservative >= the true quantile, and the p99 lands in the slow tail
    assert 0.001 <= last("paddle_t_seconds:p50") < 0.9 / 2
    assert last("paddle_t_seconds:p99") >= 0.9
    assert last("paddle_t_seconds:rate") == pytest.approx(50.0)  # 100/2s
    assert last("paddle_t_seconds:mean") == pytest.approx(0.0909, abs=0.01)

    # an interval with no new observations leaves a GAP in the derived
    # series (rate still records 0)
    p99_id = tsdb.match_series(ids, "paddle_t_seconds:p99")[0]
    n_before = len(h.query(p99_id, now=2.0)["series"][0]["points"])
    h.observe(now=4.0)
    assert len(h.query(p99_id, now=4.0)["series"][0]["points"]) == n_before
    assert last("paddle_t_seconds:rate") == 0.0


def test_query_shape_window_and_max_points():
    reg = Registry()
    g = reg.gauge("paddle_t_gauge", "probe")
    h = tsdb.MetricHistory(reg, interval_s=1.0, capacity=64)
    for i in range(30):
        g.set(float(i))
        h.observe(now=float(i))
    doc = h.query("paddle_t_gauge", window_s=5.0, max_points=3, now=29.0)
    (row,) = doc["series"]
    assert doc["window_s"] == 5.0 and row["tier"] == "raw"
    assert [p[1] for p in row["points"]] == [27.0, 28.0, 29.0]  # newest kept
    json.dumps(doc)                                    # strict-JSON-able
    assert h.query("no_such_series", now=29.0)["series"] == []


# ---------------------------------------------------------------------------
# alert engine: hold-down, multi-window AND, absence-of-data
# ---------------------------------------------------------------------------

def _burn_engine(for_s=0.0, severity="page"):
    """Private registry + history + one two-window burn rule over a gauge
    the test sets directly."""
    reg = Registry()
    g = reg.gauge("burn", "probe")
    h = tsdb.MetricHistory(reg, interval_s=1.0, capacity=256)
    rule = alerts.AlertRule(
        "test_burn",
        [alerts.AlertCondition("burn", 10.0, "avg", ">", 1.0),
         alerts.AlertCondition("burn", 60.0, "avg", ">", 1.0)],
        for_s=for_s, severity=severity)
    eng = alerts.AlertEngine(h, rules=[rule], registry=reg)
    h.add_listener(eng.evaluate)
    return reg, g, h, eng


def test_multiwindow_AND_fast_spike_does_not_fire():
    """A spike that trips the fast window while the slow window still
    averages under budget must NOT fire — the whole point of the
    fast+slow pair."""
    _reg, g, h, eng = _burn_engine()
    for t in range(0, 55):                 # 55s of zero burn
        g.set(0.0)
        h.observe(now=float(t))
    g.set(30.0)                            # hot spike
    h.observe(now=55.0)
    st = eng.states["test_burn"]
    # fast 10s window avg = 30/10 > 1, slow 60s window avg ~0.5 <= 1
    assert h.window_agg("burn", 10.0, "avg", now=55.0)["burn"] > 1.0
    assert h.window_agg("burn", 60.0, "avg", now=55.0)["burn"] <= 1.0
    assert st.state == "ok"
    # sustained burn trips BOTH windows -> fires (for_s=0)
    for t in range(56, 70):
        g.set(30.0)
        h.observe(now=float(t))
    assert st.state == "firing"
    assert st.value is not None and st.series_id == "burn"


def test_hold_down_pending_then_firing_then_clear():
    reg, g, h, eng = _burn_engine(for_s=5.0)
    st = eng.states["test_burn"]
    g.set(50.0)
    h.observe(now=100.0)
    assert st.state == "pending" and st.since == 100.0
    h.observe(now=103.0)                   # 3s held < for_s
    assert st.state == "pending"
    h.observe(now=105.0)                   # 5s held -> fires
    assert st.state == "firing" and st.fired_total == 1
    assert reg.snapshot()["paddle_alerts_firing"][
        (("alert", "test_burn"),)] == 1
    assert reg.snapshot()["paddle_alerts_fired_total"][
        (("alert", "test_burn"),)] == 1
    # recovery clears and zeroes the gauge; a NEW violation restarts the
    # hold-down from scratch
    g.set(0.0)
    for t in (200.0, 260.0, 320.0):        # flush the 60s window
        h.observe(now=t)
    assert st.state == "ok" and st.since is None
    assert reg.snapshot()["paddle_alerts_firing"][
        (("alert", "test_burn"),)] == 0
    g.set(50.0)
    h.observe(now=400.0)
    assert st.state == "pending" and st.since == 400.0


def test_absence_of_data_never_fires():
    reg = Registry()
    h = tsdb.MetricHistory(reg, interval_s=1.0, capacity=32)
    rule = alerts.AlertRule(
        "ghost", [alerts.AlertCondition("missing_series", 60.0, "max",
                                        ">", 0.0)])
    eng = alerts.AlertEngine(h, rules=[rule], registry=reg)
    for t in range(5):
        eng.evaluate(h, now=float(t))
    assert eng.states["ghost"].state == "ok"
    assert eng.health()["ok"] is True


def test_any_label_variant_violating_pages():
    """Worst-case semantics: ONE bad replica's series trips a selector
    that matches every variant."""
    reg = Registry()
    g = reg.gauge("wait", "probe")
    h = tsdb.MetricHistory(reg, interval_s=1.0, capacity=32)
    rule = alerts.AlertRule(
        "wait_high", [alerts.AlertCondition("wait", 60.0, "max", ">", 1.0)])
    eng = alerts.AlertEngine(h, rules=[rule], registry=reg)
    h.add_listener(eng.evaluate)
    g.set(0.1, replica="r0")
    g.set(9.0, replica="r1")
    h.observe(now=10.0)
    st = eng.states["wait_high"]
    assert st.state == "firing"
    assert st.series_id == 'wait{replica="r1"}' and st.value == 9.0


# ---------------------------------------------------------------------------
# alert -> flight dump with slowest journeys (exactly once per episode)
# ---------------------------------------------------------------------------

def _finish_journey(i, latency_s):
    class _Fut:
        @staticmethod
        def slo():
            return {"req_id": i, "new_tokens": 4, "queue_wait_s": 0.001,
                    "ttft_s": latency_s / 2, "tpot_s": 0.001,
                    "latency_s": latency_s}

    j = reqtrace.mint(i)
    j.event("submit", replica="router")
    j.event("admit", slot=0)
    reqtrace.finish_future(j, _Fut, "ok")
    return j.trace_id


def test_page_alert_dumps_flight_once_with_slowest_journeys(
        clean_hist, tmp_path):
    flight.enable(str(tmp_path), capacity=256)
    reqtrace.enable(ring=64)
    slow_tid = _finish_journey(1, latency_s=2.0)
    _finish_journey(2, latency_s=0.01)

    reg, g, h, eng = _burn_engine()
    g.set(50.0)
    h.observe(now=100.0)                     # fires -> dumps
    h.observe(now=101.0)                     # still firing -> NO new dump
    st = eng.states["test_burn"]
    assert st.state == "firing" and st.last_dump not in (None, "skipped")
    dumps = [f for f in os.listdir(tmp_path) if "alert-test_burn" in f]
    assert len(dumps) == 1                   # exactly one per episode
    with open(tmp_path / dumps[0]) as f:
        header = json.loads(f.readline())
    journeys = header["annotations"]["alert_slowest_journeys"]
    assert len(journeys) >= 1
    # slowest-first, joined back to full journey records
    assert journeys[0]["trace_id"] == slow_tid
    assert any(s["name"] == "admit" for s in journeys[0]["spans"])

    # clear -> a NEW episode dumps again
    g.set(0.0)
    for t in (200.0, 300.0):
        h.observe(now=t)
    assert st.state == "ok" and st.last_dump is None
    g.set(50.0)
    h.observe(now=400.0)
    assert len([f for f in os.listdir(tmp_path)
                if "alert-test_burn" in f]) == 2


def test_warn_severity_never_dumps_or_flips_health(clean_hist, tmp_path):
    flight.enable(str(tmp_path), capacity=64)
    _reg, g, h, eng = _burn_engine(severity="warn")
    g.set(50.0)
    h.observe(now=100.0)
    st = eng.states["test_burn"]
    assert st.state == "firing" and st.last_dump is None
    assert not [f for f in os.listdir(tmp_path) if "alert-" in f]
    assert eng.health()["ok"] is True        # warn does not page
    assert eng.signal()["warn_firing"] == ["test_burn"]


# ---------------------------------------------------------------------------
# exporter surfaces: /query, /alerts, /healthz alerts provider
# ---------------------------------------------------------------------------

def test_exporter_query_alerts_and_healthz_gate(clean_hist):
    rule = alerts.AlertRule(
        "probe_page",
        [alerts.AlertCondition("paddle_probe_gauge", 60.0, "max", ">", 1.0)])
    h = obs.enable_history(start_thread=False, rules=[rule])
    obs.safe_set("paddle_probe_gauge", "probe", 0.5)
    h.observe()
    with exporter.TelemetryExporter(port=0) as e:
        status, body = _get(e.url("/query?series=paddle_probe_gauge"))
        assert status == 200
        doc = json.loads(body)
        assert doc["enabled"] is True
        (row,) = doc["series"]
        assert row["id"] == "paddle_probe_gauge"
        assert row["points"][-1][1] == 0.5

        status, body = _get(e.url("/query?window=nope"))
        assert status == 400 and "bad parameter" in json.loads(body)["error"]

        status, body = _get(e.url("/alerts"))
        doc = json.loads(body)
        assert doc["enabled"] is True
        (r,) = doc["rules"]
        assert r["name"] == "probe_page" and r["state"] == "ok"

        status, body = _get(e.url("/healthz"))
        assert status == 200 and json.loads(body)["ok"] is True

        # violate -> alerts provider flips /healthz to 503 with the block
        obs.safe_set("paddle_probe_gauge", "probe", 7.0)
        h.observe()
        status, body = _get(e.url("/healthz"))
        assert status == 503
        health = json.loads(body)
        assert health["ok"] is False
        block = health["providers"]["alerts"]
        assert block["ok"] is False
        assert block["firing"][0]["name"] == "probe_page"
        assert block["firing"][0]["value"] == 7.0


def test_query_route_answers_off_plane_without_error(clean_hist):
    with exporter.TelemetryExporter(port=0) as e:
        status, body = _get(e.url("/query"))
        assert status == 200
        assert json.loads(body) == {"enabled": False, "series": []}
        status, body = _get(e.url("/alerts"))
        assert status == 200
        assert json.loads(body)["enabled"] is False


# ---------------------------------------------------------------------------
# fleet plane: obs/tsdb/rank{r} publication + /fleet/query merge
# ---------------------------------------------------------------------------

def test_fleet_query_merges_two_ranks_over_tcpstore(clean_hist):
    """Rank 1 publishes its history through a real TCPStore; rank 0
    answers /fleet/query with its own live series AND rank 1's published
    ones, window-filtered."""
    from paddlepaddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 0, is_master=True)

    # rank 1: a private history the publisher snapshots
    reg1 = Registry()
    g1 = reg1.gauge("paddle_remote_gauge", "probe")
    h1 = tsdb.MetricHistory(reg1, interval_s=1.0, capacity=32)
    # 9 points: below DOWNSAMPLE, so the published raw tier answers the
    # windowed merge (once coarse exists, a window that predates the raw
    # ring falls to the coarse tier by design)
    t0 = time.time()
    for i in range(9):
        g1.set(float(i))
        h1.observe(now=t0 + i)
    pub = aggregate.FleetPublisher(store, rank=1, interval_s=60,
                                   text_fn=lambda: "",
                                   tsdb_fn=h1.jsonable)
    pub.publish()
    assert store.check(aggregate.tsdb_key(1))

    # rank 0: live local history + fleet routes
    h0 = obs.enable_history(start_thread=False)
    obs.safe_set("paddle_local_gauge", "probe", 3.0)
    h0.observe()
    with exporter.TelemetryExporter(port=0) as e:
        aggregate.install_fleet_routes(e, store, world=2, local_rank=0)
        status, body = _get(e.url("/fleet/query?window=600"))
        assert status == 200
        doc = json.loads(body)
        assert doc["world"] == 2
        ranks = doc["ranks"]
        assert set(ranks) == {"0", "1"}
        ids0 = {s["id"] for s in ranks["0"]["series"]}
        assert "paddle_local_gauge" in ids0
        (r1row,) = [s for s in ranks["1"]["series"]
                    if s["id"] == "paddle_remote_gauge"]
        assert r1row["tier"] == "raw"
        assert [p[1] for p in r1row["points"]][-3:] == [6.0, 7.0, 8.0]

        # selector narrows the merge on both sides
        status, body = _get(
            e.url("/fleet/query?series=paddle_remote_gauge"))
        doc = json.loads(body)
        assert [s["id"] for s in doc["ranks"]["1"]["series"]] == [
            "paddle_remote_gauge"]
        assert doc["ranks"]["0"]["series"] == []

    # publication is bounded: a long history publishes at most
    # FLAGS_obs_tsdb_publish_points per tier per series
    for i in range(200):
        g1.set(float(i))
        h1.observe(now=t0 + 20 + i)
    cap = int(_flags.flag_value("obs_tsdb_publish_points"))
    doc = h1.jsonable()
    ent = doc["series"]["paddle_remote_gauge"]
    assert len(ent["raw"]) <= cap and len(ent["coarse"]) <= cap


def test_collect_fleet_tsdb_skips_silent_ranks(clean_hist):
    """A rank that never published (history plane off there) is ABSENT
    from the merge — off is not stale."""
    from paddlepaddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 0, is_master=True)
    doc = aggregate.collect_fleet_tsdb(store, world=3)
    assert doc["ranks"] == {} and doc["world"] == 3


# ---------------------------------------------------------------------------
# obsctl: query / alerts / top render, staleness warning
# ---------------------------------------------------------------------------

def test_obsctl_query_alerts_top_render(clean_hist, capsys):
    obsctl = _load_tool("obsctl")
    rule = alerts.AlertRule(
        "probe_page",
        [alerts.AlertCondition("paddle_probe_gauge", 60.0, "max", ">", 1.0)])
    h = obs.enable_history(start_thread=False, rules=[rule])
    obs.safe_set("paddle_probe_gauge", "probe", 5.0)
    obs.safe_set("paddle_router_replica_est_wait_seconds", "probe", 0.25,
                 replica="r0")
    obs.safe_set("paddle_router_replica_inflight", "probe", 2.0,
                 replica="r0")
    h.observe()
    h.observe()
    with exporter.TelemetryExporter(port=0) as e:
        target = f"127.0.0.1:{e.port}"
        assert obsctl.main(["query", target]) == 0
        out = capsys.readouterr().out
        assert "paddle_probe_gauge" in out and "last=5" in out

        assert obsctl.main(["query", target, "paddle_probe_gauge",
                            "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [s["id"] for s in doc["series"]] == ["paddle_probe_gauge"]

        assert obsctl.main(["alerts", target]) == 0
        out = capsys.readouterr().out
        assert "probe_page" in out and "FIRING" in out

        assert obsctl.main(["top", target, "--once"]) == 0
        out = capsys.readouterr().out
        assert "obsctl top" in out and "ok=False" in out
        assert "ALERTS FIRING: probe_page" in out
        assert "r0" in out                      # per-replica sparkline row
        # the sparkline glyph set is present for the est-wait series
        assert any(ch in out for ch in obsctl._SPARK)


def test_obsctl_query_reports_off_plane(clean_hist, capsys):
    obsctl = _load_tool("obsctl")
    with exporter.TelemetryExporter(port=0) as e:
        target = f"127.0.0.1:{e.port}"
        assert obsctl.main(["query", target]) == 0
        assert "history plane off" in capsys.readouterr().out
        assert obsctl.main(["alerts", target]) == 0
        assert "alert engine off" in capsys.readouterr().out
        assert obsctl.main(["top", target, "--once"]) == 0
        out = capsys.readouterr().out
        assert "alerts: engine off" in out and "history: plane off" in out


def test_obsctl_scrape_warns_on_stale_fleet_snapshot(clean_hist, capsys):
    obsctl = _load_tool("obsctl")
    obs.safe_set("paddle_fleet_snapshot_age_seconds",
                 "age of each rank's merged snapshot", 99.0, rank="1")
    obs.safe_set("paddle_fleet_snapshot_age_seconds", "", 0.1, rank="2")
    with exporter.TelemetryExporter(port=0) as e:
        target = f"127.0.0.1:{e.port}"
        assert obsctl.main(["scrape", target]) == 0
        captured = capsys.readouterr()
        assert "stale fleet snapshot" in captured.err
        assert "rank 1: 99.0s" in captured.err
        assert "rank 2" not in captured.err        # fresh rank not flagged
        # aggregate path warns through the same scan
        assert obsctl.main(["aggregate", target]) == 0
        assert "stale fleet snapshot" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# SLO-aligned histogram buckets (satellite: exact burn accounting edge)
# ---------------------------------------------------------------------------

def test_slo_aligned_buckets_helper():
    from paddlepaddle_tpu.observability import _slo_aligned_buckets
    from paddlepaddle_tpu.observability.metrics import LATENCY_BUCKETS

    assert _slo_aligned_buckets("slo_ttft_ms") is None     # unarmed
    _flags.set_flags({"slo_ttft_ms": 123.0})
    try:
        buckets = _slo_aligned_buckets("slo_ttft_ms")
        assert 0.123 in buckets and buckets == sorted(buckets)
        assert set(LATENCY_BUCKETS) <= set(buckets)
    finally:
        _flags.set_flags({"slo_ttft_ms": 0.0})


def test_ttft_buckets_align_with_armed_slo_threshold(clean_hist):
    _flags.set_flags({"slo_ttft_ms": 123.0, "slo_tpot_ms": 0.0})
    try:
        # metric REGISTRATIONS survive obs.reset() by design (hooks keep
        # their references), so an earlier test's default-bucket histogram
        # would mask the aligned one — drop them to model a fresh process
        # arming its SLO flags before first enable()
        for name in ("paddle_serving_ttft_seconds",
                     "paddle_serving_tpot_seconds"):
            obs.get_registry()._metrics.pop(name, None)
        obs.enable(trace=False, metrics=True, watchdog_=False)
        ttft = obs.get_registry().get("paddle_serving_ttft_seconds")
        assert 0.123 in ttft.buckets               # the exact SLO edge
        assert ttft.buckets == sorted(ttft.buckets)
        # unarmed flag -> the default ladder, no synthetic edge
        tpot = obs.get_registry().get("paddle_serving_tpot_seconds")
        from paddlepaddle_tpu.observability.metrics import LATENCY_BUCKETS

        assert tpot.buckets == list(LATENCY_BUCKETS)
    finally:
        _flags.set_flags({"slo_ttft_ms": 0.0})


# ---------------------------------------------------------------------------
# autoscaler consumes AlertState instead of re-deriving burn thresholds
# ---------------------------------------------------------------------------

def test_decide_defers_to_alert_signal():
    from paddlepaddle_tpu.inference.fleet import FleetPolicy, decide

    pol = FleetPolicy(min_replicas=1, max_replicas=4, up_streak=1)
    base = {"est_wait_max": 0.0, "queue_depth": 0, "replicas": 2,
            "healthy": 2}

    # alert engine armed + burn rule firing -> scale up on ITS verdict
    sig = dict(base, burn=0.2,
               alerts={"armed": True, "burn_firing": ["ttft_burn"],
                       "page_firing": ["ttft_burn"], "warn_firing": []})
    action, reason = decide(pol, sig, {}, now=0.0)
    assert action == "up" and "burn alert firing: ttft_burn" in reason

    # alert engine armed + NOT firing -> no scale-up even though the raw
    # burn number exceeds the policy threshold (one definition of
    # "violating": the rule's multi-window + hold-down, not a re-derived
    # instantaneous threshold)
    sig = dict(base, burn=50.0,
               alerts={"armed": True, "burn_firing": [],
                       "page_firing": [], "warn_firing": []})
    action, _reason = decide(pol, sig, {}, now=0.0)
    assert action is None

    # no alert engine -> the legacy threshold derivation still works
    sig = dict(base, burn=50.0)
    action, reason = decide(pol, sig, {}, now=0.0)
    assert action == "up" and "slo_burn" in reason


def test_perf_verdict_gate_from_json_doc(tmp_path):
    from paddlepaddle_tpu.inference.fleet import perf_verdict_gate

    doc = {"ok": False, "fields": [
        {"metric": "serving.aggregate_tok_s", "baseline": 100.0,
         "candidate": 80.0, "delta": 0.2, "direction": "higher",
         "verdict": "regression"},
        {"metric": "serving.tpot_ms", "baseline": 2.0, "candidate": 2.0,
         "delta": 0.0, "direction": "lower", "verdict": "ok"},
        {"metric": "serving.ttft_p50_ms", "baseline": 10.0,
         "candidate": None, "delta": None, "direction": "lower",
         "verdict": "missing"},
    ]}
    reasons = perf_verdict_gate(doc)({})
    assert len(reasons) == 2
    assert any("regression: serving.aggregate_tok_s" in r for r in reasons)
    assert any("missing: serving.ttft_p50_ms" in r for r in reasons)
    # every input form: dict, JSON string, path
    p = tmp_path / "verdict.json"
    p.write_text(json.dumps(doc))
    assert len(perf_verdict_gate(str(p))({})) == 2
    assert perf_verdict_gate(json.dumps({"ok": True, "fields": []}))({}) == []
    assert perf_verdict_gate({"ok": False, "fields": []})({}) == [
        "perf_gate verdict not ok"]
    with pytest.raises(TypeError):
        perf_verdict_gate(42)


# ---------------------------------------------------------------------------
# bench artifacts (--out) and perf_gate --json round trip
# ---------------------------------------------------------------------------

def test_serving_bench_out_artifact_feeds_perf_gate(tmp_path, capsys):
    serving_bench = _load_tool("serving_bench")
    perf_gate = _load_tool("perf_gate")

    class _Args:
        out = str(tmp_path / "BENCH_serving_r16.json")

    body = {"profile": "uniform", "aggregate_tok_s": 123.0,
            "ttft_p50_ms": 9.0}
    serving_bench._emit(body, _Args())
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line) == {"serving_bench": body}   # stdout contract
    with open(_Args.out) as f:
        art = json.load(f)
    assert art["serving_bench"] == body
    meta = art["meta"]
    assert meta["bench"] == "serving_bench"
    assert isinstance(meta["unix_time"], int) and meta["unix_time"] > 0
    assert isinstance(meta["git_sha"], str) and meta["git_sha"]
    # perf_gate loads the artifact directly and finds the gated fields
    rec = perf_gate.load_record(_Args.out)
    m = perf_gate.serving_metrics(rec)
    assert m["serving.aggregate_tok_s"] == (123.0, perf_gate.HIGHER)
    assert m["serving.ttft_p50_ms"] == (9.0, perf_gate.LOWER)

    # --out omitted: stdout only, no file
    class _NoOut:
        out = None

    serving_bench._emit({"x": 1}, _NoOut())
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1]) \
        == {"serving_bench": {"x": 1}}


def test_coldstart_bench_out_artifact(tmp_path, capsys):
    coldstart_bench = _load_tool("coldstart_bench")
    perf_gate = _load_tool("perf_gate")

    class _Args:
        out = str(tmp_path / "BENCH_coldstart_r16.json")

    body = {"preset": "tiny", "restart_to_first_token_s": 0.5,
            "compiles": 0}
    coldstart_bench._emit(body, _Args())
    capsys.readouterr()
    with open(_Args.out) as f:
        art = json.load(f)
    assert art["meta"]["bench"] == "coldstart_bench"
    m = perf_gate.serving_metrics(perf_gate.load_record(_Args.out))
    assert m["coldstart.restart_to_first_token_s"] == (0.5, perf_gate.LOWER)
    assert m["coldstart.compiles"] == (0.0, perf_gate.LOWER)


def test_perf_gate_json_verdict_shape(tmp_path, capsys):
    perf_gate = _load_tool("perf_gate")
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps({"serving_bench": {
        "aggregate_tok_s": 100.0, "ttft_p50_ms": 10.0, "tpot_ms": 2.0}}))
    cur.write_text(json.dumps({"serving_bench": {
        "aggregate_tok_s": 80.0, "tpot_ms": 2.0}}))
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps({"value": 50.0}))

    rc = perf_gate.main(["--baseline", str(bench), "--serving", str(cur),
                         str(base), "--json"])
    assert rc == 1
    captured = capsys.readouterr()
    doc = json.loads(captured.out)          # stdout is ONE strict-JSON doc
    assert doc["ok"] is False
    assert doc["regressions"] == ["serving.aggregate_tok_s"]
    assert doc["missing"] == ["serving.ttft_p50_ms"]
    by_metric = {f["metric"]: f for f in doc["fields"]}
    row = by_metric["serving.aggregate_tok_s"]
    assert row["baseline"] == 100.0 and row["candidate"] == 80.0
    assert row["delta"] == pytest.approx(0.2)
    assert row["direction"] == "higher" and row["verdict"] == "regression"
    assert by_metric["serving.ttft_p50_ms"]["verdict"] == "missing"
    assert by_metric["serving.tpot_ms"]["verdict"] == "ok"
    assert "[perf_gate]" in captured.err    # human report moved to stderr

    # the machine verdict drives the deploy gate directly
    from paddlepaddle_tpu.inference.fleet import perf_verdict_gate

    reasons = perf_verdict_gate(doc)({})
    assert any("serving.aggregate_tok_s" in r for r in reasons)

    # identical artifacts -> ok verdict, rc 0
    rc = perf_gate.main(["--baseline", str(bench), "--serving", str(base),
                         str(base), "--json"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True


# ---------------------------------------------------------------------------
# overhead: history plane armed must stay under the dispatch budget
# ---------------------------------------------------------------------------

def test_tsdb_on_overhead_under_5pct_on_microloop(clean_hist):
    """With the sampler thread live at a hot 0.05s tick (40x the default
    rate) + the default alert ruleset evaluating every tick, the eager
    dispatch loop must not notice — all sampling rides the daemon
    thread."""
    import gc
    import statistics

    import jax.numpy as jnp

    from paddlepaddle_tpu.core import dispatch

    assert dispatch._obs_op is None
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    y = paddle.to_tensor(np.ones((2, 2), np.float32))
    N = 6_000

    def loop_entry():
        t0 = time.perf_counter()
        for _ in range(N):
            dispatch.apply_op(jnp.add, x, y, op_name="add")
        return time.perf_counter() - t0

    def loop_bare():
        t0 = time.perf_counter()
        for _ in range(N):
            dispatch._apply_op(jnp.add, (x, y), {}, "add", None)
        return time.perf_counter() - t0

    loop_entry()
    loop_bare()

    def measure():
        ratios = []
        gc.disable()
        try:
            for _ in range(5):
                obs.enable_history(interval_s=0.05)
                try:
                    a = loop_entry()
                finally:
                    obs.disable_history()
                ratios.append(a / loop_bare())
        finally:
            gc.enable()
        return statistics.median(ratios) - 1.0

    overhead = measure()
    if overhead >= 0.05:       # one retry: noise spike must not fail CI
        overhead = measure()
    assert overhead < 0.05, (
        f"tsdb-on overhead {overhead:.1%} on {N}-op microloop "
        "(budget 5%)")


# ---------------------------------------------------------------------------
# acceptance drill: default ruleset under an injected latency storm
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_latency_storm_fires_ttft_burn_then_clears(clean_hist, tmp_path):
    """The ISSUE 16 acceptance: a chaos ``serving.decode`` latency storm
    against a 2-replica fleet trips the default ``ttft_burn`` rule within
    two sampler ticks; /healthz flips to 503 with the alert block;
    exactly ONE flight dump lands with >= 1 slow journey attached; the
    alert clears after the storm."""
    from paddlepaddle_tpu.inference import ServingRouter
    from paddlepaddle_tpu.resilience import chaos
    from test_serving_robustness import FakeModel, _prompt

    _flags.set_flags({"slo_ttft_ms": 100.0, "slo_tpot_ms": 0.0,
                      "slo_error_budget": 0.1, "slo_burn_window_s": 1.0})
    flight.enable(str(tmp_path), capacity=512)
    reqtrace.enable(ring=64)
    r = ServingRouter(
        [lambda: paddle.inference.ServingEngine(
            FakeModel(), mode="static", max_batch_size=2, max_wait_ms=2.0,
            max_len=64) for _ in range(2)],
        probe_interval_s=60.0)
    h = obs.enable_history(start_thread=False)   # manual sampler clock
    eng_state = alerts.get().states["ttft_burn"]
    t0 = time.time()
    try:
        # healthy traffic: fast requests, burn 0, no alert
        for _ in range(3):
            r.submit(_prompt(), max_new_tokens=2).result(30)
        h.observe(now=t0)
        assert eng_state.state == "ok"

        # the storm: every decode pays +500ms, every TTFT violates
        chaos.configure("serving.decode:latency:1.0:0.5",
                        seed=int(os.environ.get("PADDLE_CHAOS_SEED", "7")))
        for _ in range(4):
            r.submit(_prompt(), max_new_tokens=2).result(60)
        h.observe(now=t0 + 2)                    # tick 1 after onset
        if eng_state.state != "firing":
            h.observe(now=t0 + 4)                # tick 2 at the latest
        assert eng_state.state == "firing", eng_state.jsonable()

        with exporter.TelemetryExporter(port=0) as e:
            status, body = _get(e.url("/healthz"))
            assert status == 503
            block = json.loads(body)["providers"]["alerts"]
            assert block["ok"] is False
            assert any(f["name"] == "ttft_burn" for f in block["firing"])

        # exactly one dump for the episode, slow journeys attached
        h.observe(now=t0 + 6)                    # still firing: no re-dump
        dumps = [f for f in os.listdir(tmp_path) if "alert-ttft_burn" in f]
        assert len(dumps) == 1
        with open(tmp_path / dumps[0]) as f:
            header = json.loads(f.readline())
        journeys = header["annotations"]["alert_slowest_journeys"]
        assert len(journeys) >= 1
        # the attached journeys ARE storm victims: slowest-first, and the
        # worst one paid the injected latency
        assert any(s["name"] == "submit" for s in journeys[0]["spans"])

        # storm over: burn window drains, good traffic drives burn to 0,
        # and the rule clears once both windows stop violating
        chaos.disable()
        time.sleep(1.2)                          # > slo_burn_window_s
        for _ in range(3):
            r.submit(_prompt(), max_new_tokens=2).result(30)
        h.observe(now=t0 + 500)                  # storm points aged out
        assert eng_state.state == "ok", eng_state.jsonable()
        assert eng_state.last_dump is None       # next episode dumps anew
        with exporter.TelemetryExporter(port=0) as e:
            status, body = _get(e.url("/healthz"))
            assert status == 200 and json.loads(body)["ok"] is True
    finally:
        chaos.disable()
        r.stop()
        _flags.set_flags({"slo_ttft_ms": 0.0, "slo_tpot_ms": 0.0,
                          "slo_error_budget": 0.01,
                          "slo_burn_window_s": 60.0})
