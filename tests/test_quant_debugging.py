"""Quantization (QAT/PTQ) and amp.debugging sanitizer tests."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def test_fake_quant_roundtrip_and_ste():
    from paddlepaddle_tpu.quantization import FakeQuanterWithAbsMax

    q = FakeQuanterWithAbsMax()
    x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32), stop_gradient=False)
    out = q(x)
    # quantized values close to original (8-bit on [-1,1])
    assert float(np.abs(out.numpy() - x.numpy()).max()) < 1e-2
    out.sum().backward()
    assert x.grad is not None  # STE passes gradients


def test_qat_quantize_wraps_linears():
    from paddlepaddle_tpu.quantization import QAT, QuantConfig, QuantedWrapper

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(4, 8)
            self.act = paddle.nn.ReLU()
            self.fc2 = paddle.nn.Linear(8, 2)

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    net = Net()
    qat = QAT(QuantConfig())
    qnet = qat.quantize(net)
    assert isinstance(qnet.fc1, QuantedWrapper)
    x = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    out = qnet(x)
    assert out.shape == [2, 2]
    # trains
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=qnet.parameters())
    labels = np.array([0, 1])
    l0 = None
    for _ in range(5):
        loss = paddle.nn.functional.cross_entropy(qnet(x), labels)
        loss.backward(); opt.step(); opt.clear_grad()
        l0 = l0 or float(loss.numpy())
    assert float(loss.numpy()) < l0


def test_ptq_calibration():
    from paddlepaddle_tpu.quantization import PTQ

    net = paddle.nn.Linear(4, 4)
    ptq = PTQ()
    qnet = ptq.quantize(net)        # returns a copy; the FP net stays intact
    assert qnet is not net
    for _ in range(3):
        qnet(np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32))
    ptq.convert(qnet)
    assert hasattr(qnet, "_ptq_input_scale") and qnet._ptq_input_scale > 0
    assert not hasattr(net, "_ptq_input_scale")
    # the converted net must still run forward (fake-quant pre-hook wraps
    # the input in a 1-tuple, it must not iterate the Tensor's leading dim)
    x = np.random.default_rng(1).standard_normal((2, 4)).astype(np.float32)
    out = qnet(x)
    assert out.shape == [2, 4]
    assert np.isfinite(out.numpy()).all()


def test_check_numerics():
    from paddlepaddle_tpu.amp.debugging import DebugMode, check_numerics

    t = paddle.to_tensor(np.array([1.0, np.nan, np.inf, 0.0], np.float32))
    with pytest.raises(FloatingPointError):
        check_numerics(t, "op", "t")
    n_nan, n_inf, n_zero = check_numerics(t, "op", "t", DebugMode.CHECK_NAN_INF)
    assert int(n_nan.numpy()) == 1 and int(n_inf.numpy()) == 1 and int(n_zero.numpy()) == 1


def test_tensor_checker_catches_nan_op():
    from paddlepaddle_tpu.amp.debugging import (
        TensorCheckerConfig,
        disable_tensor_checker,
        enable_tensor_checker,
    )

    enable_tensor_checker(TensorCheckerConfig(enable=True))
    try:
        x = paddle.to_tensor(np.array([0.0], np.float32))
        with pytest.raises(FloatingPointError):
            _ = x / x  # 0/0 -> NaN
    finally:
        disable_tensor_checker()
    # after disable it must not raise
    x = paddle.to_tensor(np.array([0.0], np.float32))
    _ = x / x


def test_operator_stats_collection(capsys):
    from paddlepaddle_tpu.amp.debugging import collect_operator_stats

    with collect_operator_stats():
        a = paddle.to_tensor(np.ones((2, 2), np.float32))
        _ = a @ a
    out = capsys.readouterr().out
    assert "op list" in out
