"""OpTest-style harness (reference: test/legacy_test/op_test.py:418):
check forward against a numpy reference and gradients against numeric
finite differences, across dtypes and eager/jit modes."""

from __future__ import annotations

import numpy as np

import paddlepaddle_tpu as paddle


def check_forward(fn, np_fn, arrays, rtol=1e-5, atol=1e-6, kwargs=None):
    kwargs = kwargs or {}
    tensors = [paddle.to_tensor(a) for a in arrays]
    out = fn(*tensors, **kwargs)
    ref = np_fn(*arrays, **kwargs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    refs = ref if isinstance(ref, (list, tuple)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(o.numpy().astype(np.float64),
                                   np.asarray(r, np.float64), rtol=rtol, atol=atol)
    return out


def numeric_grad(fn, arrays, idx, out_grad=None, eps=1e-3, kwargs=None):
    """Central finite differences of sum(fn * out_grad) wrt arrays[idx]."""
    kwargs = kwargs or {}

    def scalar_out(*arrs):
        tensors = [paddle.to_tensor(a) for a in arrs]
        out = fn(*tensors, **kwargs)
        out_np = out.numpy().astype(np.float64)
        if out_grad is None:
            return out_np.sum()
        return (out_np * out_grad).sum()

    x = arrays[idx].astype(np.float64)
    grad = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        orig = x[i]
        pert = list(arrays)
        xp = x.copy()
        xp[i] = orig + eps
        pert[idx] = xp.astype(arrays[idx].dtype)
        f1 = scalar_out(*pert)
        xm = x.copy()
        xm[i] = orig - eps
        pert[idx] = xm.astype(arrays[idx].dtype)
        f2 = scalar_out(*pert)
        grad[i] = (f1 - f2) / (2 * eps)
        it.iternext()
    return grad


def check_grad(fn, arrays, grad_idx=None, rtol=1e-2, atol=1e-3, eps=1e-3, kwargs=None):
    """Compare tape backward() grads against numeric finite differences."""
    kwargs = kwargs or {}
    grad_idx = grad_idx if grad_idx is not None else list(range(len(arrays)))
    tensors = []
    for i, a in enumerate(arrays):
        t = paddle.to_tensor(a)
        if i in grad_idx:
            t.stop_gradient = False
        tensors.append(t)
    out = fn(*tensors, **kwargs)
    loss = out.sum() if out.size > 1 else out
    loss.backward()
    for i in grad_idx:
        analytic = tensors[i].grad.numpy().astype(np.float64)
        numeric = numeric_grad(fn, arrays, i, eps=eps, kwargs=kwargs)
        np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch for input {i}")
