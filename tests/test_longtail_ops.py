"""Long-tail tensor ops (ops/longtail.py) vs numpy/scipy/torch references,
plus a namespace-coverage check against the reference's tensor exports."""

import numpy as np
import pytest
import scipy.linalg
import scipy.special
import torch

import paddlepaddle_tpu as paddle

rng = np.random.default_rng(0)
A34 = rng.standard_normal((3, 4)).astype(np.float32)
PD = (A34 @ A34.T + 4 * np.eye(3)).astype(np.float32)


def test_add_n_atleast_invert_blockdiag():
    np.testing.assert_allclose(
        paddle.add_n([paddle.to_tensor(A34), paddle.to_tensor(A34)]).numpy(),
        2 * A34)
    assert paddle.atleast_1d(np.float32(3)).shape == [1]
    assert paddle.atleast_2d(np.float32(3)).shape == [1, 1]
    assert paddle.atleast_3d(A34).shape == [3, 4, 1]
    np.testing.assert_array_equal(
        paddle.bitwise_invert(np.array([1, 2], np.int32)).numpy(),
        ~np.array([1, 2], np.int32))
    bd = paddle.block_diag([np.eye(2, dtype=np.float32),
                            np.full((1, 2), 7, np.float32)]).numpy()
    assert bd.shape == (3, 4) and bd[2, 2] == 7


def test_linalg_tail():
    L = np.linalg.cholesky(PD)
    np.testing.assert_allclose(paddle.cholesky_inverse(L).numpy(),
                               np.linalg.inv(PD), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(paddle.cond(PD).numpy(), np.linalg.cond(PD),
                               rtol=1e-4)
    np.testing.assert_allclose(paddle.cond(PD, p="fro").numpy(),
                               np.linalg.cond(PD, "fro"), rtol=1e-4)

    lu, piv = scipy.linalg.lu_factor(PD)
    P, Lu, U = paddle.lu_unpack(lu.astype(np.float32),
                                (piv + 1).astype(np.int32))
    np.testing.assert_allclose(P.numpy() @ Lu.numpy() @ U.numpy(), PD,
                               rtol=1e-4, atol=1e-4)

    u, s, v = paddle.svd_lowrank(A34, q=2)
    assert u.shape == [3, 2] and s.shape == [2] and v.shape == [4, 2]
    ref_s = np.linalg.svd(A34, compute_uv=False)[:2]
    np.testing.assert_allclose(s.numpy(), ref_s, rtol=1e-4)
    u2, s2, v2 = paddle.pca_lowrank(A34, q=2)
    centered = A34 - A34.mean(0)
    np.testing.assert_allclose(
        s2.numpy(), np.linalg.svd(centered, compute_uv=False)[:2], rtol=1e-4)

    # ormqr: Q @ other from the LAPACK householder (geqrf) form
    (h, tau), _ = scipy.linalg.qr(PD, mode="raw")
    h = np.asarray(h, np.float32).copy()
    tau = np.asarray(tau, np.float32)
    other = rng.standard_normal((3, 2)).astype(np.float32)
    q = scipy.linalg.qr(PD)[0].astype(np.float32)
    out = paddle.ormqr(h, tau, other).numpy()
    np.testing.assert_allclose(np.abs(out), np.abs(q @ other), rtol=1e-3,
                               atol=1e-4)


def test_special_functions():
    x = np.array([0.5, 1.5, 3.0], np.float32)
    y = np.array([1.0, 2.0, 0.5], np.float32)
    np.testing.assert_allclose(paddle.gammainc(x, y).numpy(),
                               scipy.special.gammainc(x, y), rtol=1e-5)
    np.testing.assert_allclose(paddle.gammaincc(x, y).numpy(),
                               scipy.special.gammaincc(x, y), rtol=1e-5)
    np.testing.assert_allclose(paddle.multigammaln(np.array([3.0], np.float32), 2).numpy(),
                               scipy.special.multigammaln(3.0, 2), rtol=1e-5)
    np.testing.assert_allclose(paddle.polygamma(x, 1).numpy(),
                               scipy.special.polygamma(1, x), rtol=1e-4)


def test_scatter_fill_select():
    d = rng.standard_normal(3).astype(np.float32)
    x2 = rng.standard_normal((4, 4)).astype(np.float32)
    np.testing.assert_allclose(
        paddle.diagonal_scatter(x2, d, offset=1).numpy(),
        torch.diagonal_scatter(torch.tensor(x2), torch.tensor(d),
                               offset=1).numpy())
    out = paddle.index_fill(x2, np.array([0, 2], np.int64), 0, 9.0).numpy()
    assert (out[0] == 9).all() and (out[2] == 9).all() and (out[1] != 9).any()
    ss = paddle.select_scatter(np.zeros((2, 3), np.float32),
                               np.ones(3, np.float32), 0, 1).numpy()
    np.testing.assert_array_equal(ss, [[0, 0, 0], [1, 1, 1]])


def test_misc_tail():
    y = rng.standard_normal(6).astype(np.float32)
    np.testing.assert_allclose(
        paddle.cumulative_trapezoid(y, dx=0.5).numpy(),
        torch.cumulative_trapezoid(torch.tensor(y), dx=0.5).numpy(),
        rtol=1e-5)
    m, e = paddle.frexp(np.array([8.0, 0.5], np.float32))
    np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), [8.0, 0.5])
    assert paddle.isin(np.array([1, 2, 3]),
                       np.array([2])).numpy().tolist() == [False, True, False]
    assert paddle.is_floating_point(paddle.to_tensor(A34))
    assert paddle.is_integer(paddle.to_tensor(np.array([1])))
    assert not paddle.is_complex(paddle.to_tensor(A34))
    r = paddle.reduce_as(np.ones((2, 3, 4), np.float32),
                         np.ones((3, 1), np.float32))
    assert r.shape == [3, 1] and float(r.numpy()[0, 0]) == 8
    np.testing.assert_array_equal(
        paddle.reverse(np.arange(3), 0).numpy(), [2, 1, 0])
    np.testing.assert_allclose(paddle.positive(A34).numpy(), A34)
    u = paddle.unstack(np.arange(6).reshape(2, 3))
    assert len(u) == 2 and u[1].numpy().tolist() == [3, 4, 5]
    edges = paddle.histogram_bin_edges(A34, bins=4).numpy()
    assert edges.shape == (5,)
    hist, hedges = paddle.histogramdd(rng.standard_normal((20, 2)).astype(np.float32),
                                      bins=4)
    assert hist.shape == [4, 4] and len(hedges) == 2


def test_stft_istft_roundtrip_vs_torch():
    sig = rng.standard_normal(512).astype(np.float32)
    win = np.hanning(128).astype(np.float32)
    S = paddle.stft(sig, n_fft=128, hop_length=32, window=win)
    St = torch.stft(torch.tensor(sig), n_fft=128, hop_length=32,
                    window=torch.tensor(win), center=True,
                    pad_mode="reflect", return_complex=True).numpy()
    np.testing.assert_allclose(S.numpy(), St, rtol=1e-3, atol=1e-4)
    rec = paddle.istft(S, n_fft=128, hop_length=32, window=win, length=512)
    np.testing.assert_allclose(rec.numpy(), sig, atol=1e-4)


def test_top_p_sampling():
    probs = np.array([[0.5, 0.3, 0.15, 0.05]], np.float32)
    hits = set()
    for s in range(12):
        _, ids = paddle.top_p_sampling(probs, np.float32(0.8), seed=s)
        hits.add(int(ids.numpy()[0, 0]))
    assert hits <= {0, 1, 2}  # the 0.05 tail is excluded at p=0.8
    assert len(hits) >= 2


def test_reference_tensor_namespace_closed():
    """Every reference python/paddle/tensor export exists here."""
    import os
    import re

    if not os.path.exists("/root/reference"):
        pytest.skip("reference tree not present")
    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    ref = set(re.findall(r"'(\w+)'", src))
    missing = sorted(n for n in ref
                     if not hasattr(paddle, n)
                     and not n.endswith("_") and not n.startswith("_"))
    assert missing == [], f"missing reference tensor exports: {missing}"


def test_top_level_namespace_closed():
    """Every real reference python/paddle export exists (excluding the
    regex's build-env string captures)."""
    import os
    import re

    if not os.path.exists("/root/reference"):
        pytest.skip("reference tree not present")
    src = open("/root/reference/python/paddle/__init__.py").read()
    ref = set(re.findall(r"'(\w+)'", src))
    junk = {"32_", "AMD64", "AddDllDirectory", "CINN_CONFIG_PATH", "Library",
            "Linux", "ON", "PATH", "ProgramFiles", "Windows", "bin", "libs",
            "nvidia", "raw", "runtime_include_dir", "win32", "x86_64",
            "pstring", "batch", "dtype", "bool"}
    missing = sorted(n for n in ref if not hasattr(paddle, n)
                     and not n.startswith("_") and n not in junk)
    assert missing == [], f"missing top-level exports: {missing}"


def test_inplace_variants_and_stacks():
    t = paddle.to_tensor(np.array([4.0, 9.0], np.float32))
    out = paddle.sqrt_(t)
    assert out is t
    np.testing.assert_allclose(t.numpy(), [2.0, 3.0])
    np.testing.assert_allclose(
        paddle.hstack([np.ones(2, np.float32), np.zeros(2, np.float32)]).numpy(),
        [1, 1, 0, 0])
    np.testing.assert_allclose(
        paddle.vstack([np.ones(2, np.float32), np.zeros(2, np.float32)]).numpy(),
        [[1, 1], [0, 0]])
    cp = paddle.cartesian_prod([np.array([0, 1]), np.array([5, 6])]).numpy()
    assert cp.shape == (4, 2) and list(cp[0]) == [0, 5]
    cb = paddle.combinations(np.array([1, 2, 3])).numpy()
    assert cb.shape == (3, 2)
    d = paddle.pdist(np.array([[0.0, 0], [3, 4]], np.float32)).numpy()
    np.testing.assert_allclose(d, [5.0])
    v = paddle.vecdot(np.ones((2, 3), np.float32),
                      np.ones((2, 3), np.float32)).numpy()
    np.testing.assert_allclose(v, [3, 3])
    r = paddle.renorm(np.array([[3.0, 4.0], [0.3, 0.4]], np.float32),
                      p=2.0, axis=0, max_norm=1.0).numpy()
    np.testing.assert_allclose(np.linalg.norm(r[0]), 1.0, rtol=1e-5)
    np.testing.assert_allclose(r[1], [0.3, 0.4], rtol=1e-5)  # under the cap
