"""Core Tensor + creation + math op tests (reference model:
test/legacy_test/test_* API tests comparing against numpy)."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from op_test import check_forward, check_grad


def test_to_tensor_basics():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == np.float32
    assert t.ndim == 2
    assert t.size == 4
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])
    assert t.stop_gradient


def test_dtype_conversion():
    t = paddle.to_tensor([1, 2, 3], dtype="int64")
    assert t.dtype == np.int64
    f = t.astype("float32")
    assert f.dtype == np.float32
    b = paddle.to_tensor([1.0], dtype="bfloat16")
    assert str(b.dtype) == "bfloat16"


def test_default_dtype():
    paddle.set_default_dtype("float32")
    assert paddle.get_default_dtype() == np.float32
    t = paddle.to_tensor([1.5])
    assert t.dtype == np.float32


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([2, 3]).numpy().sum() == 6
    np.testing.assert_array_equal(paddle.full([2], 7).numpy(), [7, 7])
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3, dtype=np.float32))
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6)
    x = paddle.to_tensor([[1.0, 2], [3, 4]])
    np.testing.assert_array_equal(paddle.zeros_like(x).numpy(), np.zeros((2, 2)))
    np.testing.assert_array_equal(paddle.tril(x).numpy(), np.tril(x.numpy()))
    np.testing.assert_array_equal(paddle.triu(x).numpy(), np.triu(x.numpy()))


def test_random_creation():
    paddle.seed(42)
    a = paddle.rand([100])
    assert 0 <= a.numpy().min() and a.numpy().max() < 1
    b = paddle.randn([1000])
    assert abs(b.numpy().mean()) < 0.2
    c = paddle.randint(0, 10, [100])
    assert c.numpy().min() >= 0 and c.numpy().max() < 10
    p = paddle.randperm(10)
    assert sorted(p.numpy().tolist()) == list(range(10))
    # determinism
    paddle.seed(7)
    x1 = paddle.rand([4]).numpy()
    paddle.seed(7)
    x2 = paddle.rand([4]).numpy()
    np.testing.assert_array_equal(x1, x2)


def test_operators():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((x + y).numpy(), [5, 7, 9])
    np.testing.assert_allclose((x - y).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((x * y).numpy(), [4, 10, 18])
    np.testing.assert_allclose((y / x).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((x**2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2 + x).numpy(), [3, 4, 5])
    np.testing.assert_allclose((-x).numpy(), [-1, -2, -3])
    assert (x < y).numpy().all()
    assert (x == x).numpy().all()
    m = paddle.to_tensor([[1.0, 2], [3, 4]])
    np.testing.assert_allclose((m @ m).numpy(), m.numpy() @ m.numpy())


def test_math_unary_forward():
    x = np.random.uniform(0.1, 2.0, (3, 4)).astype(np.float32)
    for name in ["exp", "log", "sqrt", "sin", "cos", "tanh", "abs", "floor",
                 "ceil", "square", "rsqrt", "sigmoid", "erf", "log1p"]:
        np_ref = {
            "rsqrt": lambda a: 1 / np.sqrt(a),
            "sigmoid": lambda a: 1 / (1 + np.exp(-a)),
            "square": lambda a: a * a,
            "erf": lambda a: np.vectorize(__import__("math").erf)(a).astype(np.float64),
        }.get(name, getattr(np, name, None))
        check_forward(getattr(paddle, name), np_ref, [x], rtol=1e-3, atol=1e-5)


def test_reductions():
    x = np.random.randn(3, 4, 5).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(t.sum().numpy(), x.sum(), rtol=1e-5)
    np.testing.assert_allclose(t.mean(axis=1).numpy(), x.mean(1), rtol=1e-5)
    np.testing.assert_allclose(t.max(axis=-1).numpy(), x.max(-1), rtol=1e-6)
    np.testing.assert_allclose(t.min().numpy(), x.min(), rtol=1e-6)
    np.testing.assert_allclose(t.prod(axis=0).numpy(), x.prod(0), rtol=1e-4)
    np.testing.assert_allclose(t.std(axis=1).numpy(), x.std(1, ddof=1), rtol=1e-4)
    np.testing.assert_allclose(t.var().numpy(), x.var(ddof=1), rtol=1e-4)
    np.testing.assert_allclose(
        paddle.logsumexp(t, axis=2).numpy(),
        np.log(np.exp(x).sum(2)), rtol=1e-4)
    assert paddle.all(paddle.to_tensor([True, True])).numpy()
    assert paddle.any(paddle.to_tensor([False, True])).numpy()


def test_manipulation():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    t = paddle.to_tensor(x)
    assert t.reshape([6, 4]).shape == [6, 4]
    assert t.reshape([-1]).shape == [24]
    assert t.transpose([2, 0, 1]).shape == [4, 2, 3]
    assert t.flatten().shape == [24]
    assert t.flatten(1, 2).shape == [2, 12]
    assert t.unsqueeze(0).shape == [1, 2, 3, 4]
    assert t.unsqueeze(0).squeeze(0).shape == [2, 3, 4]
    c = paddle.concat([t, t], axis=1)
    assert c.shape == [2, 6, 4]
    s = paddle.stack([t, t], axis=0)
    assert s.shape == [2, 2, 3, 4]
    parts = paddle.split(t, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    np.testing.assert_array_equal(t.tile([2, 1, 1]).numpy(), np.tile(x, (2, 1, 1)))
    np.testing.assert_array_equal(
        paddle.expand(paddle.to_tensor([[1.0], [2.0]]), [2, 3]).numpy(),
        np.broadcast_to([[1.0], [2.0]], (2, 3)))
    np.testing.assert_array_equal(t.flip([0]).numpy(), x[::-1])
    np.testing.assert_array_equal(t.roll(1, axis=0).numpy(), np.roll(x, 1, 0))


def test_indexing():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    t = paddle.to_tensor(x)
    np.testing.assert_array_equal(t[0].numpy(), x[0])
    np.testing.assert_array_equal(t[1:3].numpy(), x[1:3])
    np.testing.assert_array_equal(t[:, 2].numpy(), x[:, 2])
    np.testing.assert_array_equal(t[..., -1].numpy(), x[..., -1])
    np.testing.assert_array_equal(t[t > 10].numpy(), x[x > 10])
    idx = paddle.to_tensor([0, 2], dtype="int32")
    np.testing.assert_array_equal(t[idx].numpy(), x[[0, 2]])
    # setitem
    t2 = paddle.to_tensor(x.copy())
    t2[0] = 0.0
    assert t2.numpy()[0].sum() == 0
    t2[1:3, 2] = 9.0
    assert (t2.numpy()[1:3, 2] == 9).all()


def test_gather_scatter():
    x = np.random.randn(5, 3).astype(np.float32)
    t = paddle.to_tensor(x)
    idx = paddle.to_tensor([0, 3], dtype="int64")
    np.testing.assert_array_equal(paddle.gather(t, idx).numpy(), x[[0, 3]])
    u = np.random.randn(2, 3).astype(np.float32)
    out = paddle.scatter(t, idx, paddle.to_tensor(u))
    ref = x.copy()
    ref[[0, 3]] = u
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)
    np.testing.assert_array_equal(
        paddle.index_select(t, idx, axis=0).numpy(), x[[0, 3]])
    nd_idx = paddle.to_tensor([[0, 1], [2, 2]], dtype="int64")
    np.testing.assert_array_equal(paddle.gather_nd(t, nd_idx).numpy(), x[[0, 2], [1, 2]])


def test_search_sort():
    x = np.random.randn(4, 6).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_array_equal(paddle.argmax(t, axis=1).numpy(), x.argmax(1))
    np.testing.assert_array_equal(paddle.argsort(t, axis=-1).numpy(), x.argsort(-1))
    np.testing.assert_allclose(paddle.sort(t, axis=0).numpy(), np.sort(x, 0), rtol=1e-6)
    vals, idx = paddle.topk(t, 3, axis=1)
    ref = -np.sort(-x, axis=1)[:, :3]
    np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
    nz = paddle.nonzero(paddle.to_tensor([0, 1, 0, 2]))
    np.testing.assert_array_equal(nz.numpy().reshape(-1), [1, 3])


def test_linalg():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4, 5).astype(np.float32)
    np.testing.assert_allclose(paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
                               a @ b, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b.T), transpose_y=True).numpy(),
        a @ b, rtol=1e-4, atol=1e-5)
    sq = np.random.randn(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
    np.testing.assert_allclose(paddle.linalg.inv(paddle.to_tensor(sq)).numpy(),
                               np.linalg.inv(sq), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(paddle.linalg.det(paddle.to_tensor(sq)).numpy(),
                               np.linalg.det(sq), rtol=1e-4)
    np.testing.assert_allclose(
        paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
        np.einsum("ij,jk->ik", a, b), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(paddle.norm(paddle.to_tensor(a)).numpy(),
                               np.linalg.norm(a), rtol=1e-5)


def test_inplace_ops():
    t = paddle.to_tensor([1.0, 2.0])
    t.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(t.numpy(), [2, 3])
    t.scale_(2.0)
    np.testing.assert_allclose(t.numpy(), [4, 6])
    t.set_value(np.array([7.0, 8.0], np.float32))
    np.testing.assert_allclose(t.numpy(), [7, 8])


def test_cast_where_clip():
    x = np.random.randn(3, 4).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.clip(t, -0.5, 0.5).numpy(), np.clip(x, -0.5, 0.5))
    w = paddle.where(t > 0, t, paddle.zeros_like(t))
    np.testing.assert_allclose(w.numpy(), np.where(x > 0, x, 0))
    np.testing.assert_array_equal(paddle.cast(t, "int32").numpy(), x.astype(np.int32))


def test_item_and_interop():
    t = paddle.to_tensor([3.5])
    assert t.item() == pytest.approx(3.5)
    assert float(paddle.to_tensor(2.0)) == 2.0
    assert len(paddle.zeros([5, 2])) == 5
    assert np.asarray(paddle.ones([2])).sum() == 2


def test_tensor_method_tail_complete():
    """Every name in the reference's tensor_method_func patch list
    (python/paddle/tensor/__init__.py) resolves on a Tensor instance —
    the round-4 method-tail closure."""
    import os
    import re

    ref = "/root/reference/python/paddle/tensor/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference checkout not available on this machine")
    src = open(ref).read()
    names = sorted(set(re.findall(
        r"'(\w+)'", src.split("tensor_method_func")[1].split("]")[0])))
    assert len(names) > 350
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    missing = [n for n in names if not hasattr(t, n)]
    assert not missing, missing


def test_tensor_method_tail_semantics():
    x = np.array([[4.0, 1.0], [2.0, 8.0]], np.float32)

    # plain tail methods dispatch to the top-level functions
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(t.tril().numpy(), np.tril(x))
    np.testing.assert_allclose(t.diag().numpy(), np.diag(x))
    assert t.is_floating_point() and not t.is_complex()
    np.testing.assert_allclose(
        paddle.to_tensor(x).atleast_3d().numpy().shape, (2, 2, 1))

    # in-place tail: rebind semantics, returns self, version bumps
    t = paddle.to_tensor(x)
    v0 = t._version
    out = t.log_()
    assert out is t and t._version > v0
    np.testing.assert_allclose(t.numpy(), np.log(x), rtol=1e-6)
    t.transpose_([1, 0])
    np.testing.assert_allclose(t.numpy(), np.log(x).T, rtol=1e-6)
    t.cast_("float64")
    assert t.numpy().dtype == np.float64
    b = paddle.to_tensor(x).equal_(paddle.to_tensor(x))
    assert b.numpy().all()

    # random fills: shape/dtype preserved, values in-range, deterministic
    # under paddle.seed
    paddle.seed(7)
    u = paddle.to_tensor(np.zeros((64,), np.float32)).uniform_(0.25, 0.75)
    assert (u.numpy() >= 0.25).all() and (u.numpy() <= 0.75).all()
    paddle.seed(7)
    u2 = paddle.to_tensor(np.zeros((64,), np.float32)).uniform_(0.25, 0.75)
    np.testing.assert_array_equal(u.numpy(), u2.numpy())
    bern = paddle.to_tensor(np.zeros((100,), np.float32)).bernoulli_(0.5)
    assert set(np.unique(bern.numpy())) <= {0.0, 1.0}

    # set_: reference strided-view semantics by value
    src2 = paddle.to_tensor(np.array([11., 22., 33., 44., 55., 66.],
                                     np.float32))
    t = paddle.to_tensor(np.ones((5,), np.float32))
    t.set_(src2, shape=[3], stride=[2])
    np.testing.assert_allclose(t.numpy(), [11., 33., 55.])
    t2 = paddle.to_tensor(np.ones((5,), np.float32))
    t2.set_(src2, shape=[5], offset=4)      # byte offset, as in the reference
    np.testing.assert_allclose(t2.numpy(), [22., 33., 44., 55., 66.])
    t3 = paddle.to_tensor(np.ones((3,), np.float32))
    assert t3.set_().shape == [0]

    # leaf-with-grad guard matches the in-place policy
    g = paddle.to_tensor(x, stop_gradient=False)
    with pytest.raises((RuntimeError, ValueError)):
        g.set_(src2)


def test_inplace_variant_sweep():
    """Every generated in-place method: result equals the out-of-place op,
    the SAME Tensor object is returned, and the version counter bumps
    (reference inplace contract, eager_method.cc TensorWrapper rules)."""
    f1 = np.random.default_rng(3).uniform(0.2, 0.8, (2, 4)).astype(np.float32)
    other = np.random.default_rng(4).uniform(0.2, 0.8, (2, 4)).astype(np.float32)
    i1 = np.random.default_rng(5).integers(1, 8, (2, 4)).astype(np.int32)
    b1 = np.array([[True, False], [False, True]])

    # erf_/expm1_ are top-level-only in the reference's method list
    unary_f = ["abs", "acos", "asin", "atan", "ceil", "cos", "cosh",
               "erfinv", "exp", "floor", "frac", "lgamma", "log",
               "log10", "log1p", "log2", "neg", "reciprocal", "round",
               "rsqrt", "sigmoid", "sin", "sinh",
               "sqrt", "square", "tan", "tanh", "trunc", "digamma", "i0",
               "logit", "nan_to_num", "sinc", "gammaln"]
    for name in unary_f:
        t = paddle.to_tensor(f1)
        v0 = t._version
        out = getattr(t, name + "_")()
        assert out is t and t._version > v0, name
        want = getattr(paddle, name)(paddle.to_tensor(f1)).numpy()
        np.testing.assert_allclose(t.numpy(), want, rtol=1e-5, atol=1e-6,
                                   err_msg=name)

    binary_f = ["add", "subtract", "multiply", "divide", "pow", "copysign",
                "hypot", "floor_divide", "floor_mod", "mod", "ldexp"]
    for name in binary_f:
        t = paddle.to_tensor(f1)
        o = paddle.to_tensor(other)
        out = getattr(t, name + "_")(o)
        assert out is t, name
        want = getattr(paddle, name)(paddle.to_tensor(f1), o).numpy()
        np.testing.assert_allclose(t.numpy(), want, rtol=1e-5, atol=1e-6,
                                   err_msg=name)

    int_binary = ["gcd", "lcm", "bitwise_and", "bitwise_or", "bitwise_xor",
                  "bitwise_left_shift", "bitwise_right_shift"]
    for name in int_binary:
        t = paddle.to_tensor(i1)
        out = getattr(t, name + "_")(paddle.to_tensor(i1))
        assert out is t, name
        want = getattr(paddle, name)(paddle.to_tensor(i1),
                                     paddle.to_tensor(i1)).numpy()
        np.testing.assert_array_equal(t.numpy(), want, err_msg=name)

    # comparison / logical in-place rebind to bool results
    t = paddle.to_tensor(f1)
    t.greater_than_(paddle.to_tensor(other))
    np.testing.assert_array_equal(t.numpy(), f1 > other)
    t = paddle.to_tensor(b1)
    t.logical_xor_(paddle.to_tensor(b1))
    assert not t.numpy().any()

    # shape-rewriting in-place
    t = paddle.to_tensor(f1)
    t.unsqueeze_(0)
    assert t.shape == [1, 2, 4]
    t.squeeze_(0)
    assert t.shape == [2, 4]
    t.flatten_()
    assert t.shape == [8]
    t = paddle.to_tensor(f1)
    t.t_()
    assert t.shape == [4, 2]
    np.testing.assert_allclose(t.numpy(), f1.T)
    sq = paddle.to_tensor(f1 @ other.T)
    sq.tril_()
    assert np.allclose(sq.numpy(), np.tril(f1 @ other.T))
    sq.triu_()
    assert np.allclose(sq.numpy(), np.triu(np.tril(f1 @ other.T)))

    # tape interaction: in-place on an intermediate keeps upstream grads
    x = paddle.to_tensor(f1, stop_gradient=False)
    y = (x * 2.0)
    y.exp_()
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2.0 * np.exp(2.0 * f1),
                               rtol=1e-5, err_msg="inplace tape grad")
    # leaf guard still applies
    leaf = paddle.to_tensor(f1, stop_gradient=False)
    with pytest.raises(RuntimeError, match="leaf"):
        leaf.exp_()
