"""Speculative decoding (inference/speculative.py + engine wiring).

The acceptance surface of ROADMAP item 1: greedy speculative decode is
token-EXACT vs the non-speculative engine (weak independent draft — the
heavy-rejection path — and self-draft — the full-acceptance path,
including the draft-cache catch-up deficit it creates), tokens per
target step > 1 at full acceptance, rejected runs leave ZERO leaked
pages and intact prefix-cache refcounts (the page-rewind rollback is an
index edit), the compile plan enumerates draft_admit/draft_k/verify_k as
first-class entries (warmup -> compile-free serve window; bundle round
trip with zero cold compiles; a draft-model swap fails the fingerprint
gate loudly), and multi-token steps report honest TPOT. The int8-draft
and k-sweep variants ride the `slow` marker (tier-1 budget)."""

import os
import time

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.inference import compile_plan as cp
from paddlepaddle_tpu.inference.decode_engine import BatchDecodeEngine
from paddlepaddle_tpu.inference.robustness import (
    RequestCancelledError,
    RequestValidationError,
)
from paddlepaddle_tpu.inference.serving import GenerationResult, ServingEngine
from paddlepaddle_tpu.observability import watchdog


def _llama(hidden=64, layers=2, vocab=128, max_len=96, dtype="bfloat16"):
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    return LlamaForCausalLM(LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 3,
        num_hidden_layers=layers, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=max_len,
        dtype=dtype))


@pytest.fixture(scope="module")
def target():
    paddle.seed(0)
    return _llama()


@pytest.fixture(scope="module")
def draft_weak():
    """An INDEPENDENT small draft: with random weights it almost never
    matches the target's greedy choice, so every verify step exercises
    the rejection/rollback path — the adversarial parity workload."""
    paddle.seed(7)
    return _llama(hidden=32)


@pytest.fixture(scope="module")
def workload():
    """Ragged prompts + budgets, one eos request, one shared prefix pair
    (page-aligned at page_size 16, MISS then HIT)."""
    rng = np.random.default_rng(3)
    reqs = []
    for plen, budget, eos in [(5, 8, None), (17, 4, None), (40, 6, None),
                              (9, 8, 3), (22, 5, None)]:
        reqs.append((rng.integers(0, 128, (plen,)).astype(np.int32),
                     budget, eos, None))
    system = rng.integers(0, 128, (16,)).astype(np.int32)
    for _ in range(2):
        tail = rng.integers(0, 128, (7,)).astype(np.int32)
        reqs.append((np.concatenate([system, tail]), 6, None, 16))
    return reqs


def _refs(target, workload):
    """Per-request greedy ground truth (generate_cached, trimmed the way
    the engine trims: up to and including eos, budget-bounded)."""
    outs = []
    for p, budget, eos, _ in workload:
        outs.append(target.generate_cached(
            p[None], max_new_tokens=budget, temperature=0.0,
            eos_token_id=eos).numpy()[0])
    return outs


@pytest.fixture(scope="module")
def spec_engine(target, draft_weak):
    eng = ServingEngine(target, max_batch_size=3, decode_chunk=8,
                        kv_page_size=16, draft=draft_weak, spec_k=2)
    yield eng
    eng.stop()


def _submit_all(eng, workload):
    return [eng.submit(p, max_new_tokens=budget, eos_token_id=eos,
                       prefix_len=pfx)
            for p, budget, eos, pfx in workload]


# -- units -------------------------------------------------------------------

def test_spec_plan_keys_parse_and_validation():
    assert cp.parse_key(cp.draft_admit_key(128)) == (
        "draft_admit", {"bucket": 128})
    assert cp.parse_key(cp.draft_key(4)) == ("draft", {"k": 4})
    assert cp.parse_key(cp.verify_key(4)) == ("verify", {"k": 4})
    with pytest.raises(ValueError, match="unrecognized"):
        cp.parse_key("verify_kx")


def test_spec_constructor_validation(target, draft_weak):
    with pytest.raises(ValueError, match="BOTH draft"):
        BatchDecodeEngine(target, max_slots=2, spec_k=2)
    with pytest.raises(ValueError, match="paged"):
        BatchDecodeEngine(target, max_slots=2, kv_layout="contiguous",
                          draft=draft_weak, spec_k=2)
    with pytest.raises(ValueError, match="vocab"):
        paddle.seed(11)
        BatchDecodeEngine(target, max_slots=2,
                          draft=_llama(hidden=32, vocab=64), spec_k=2)
    with pytest.raises(ValueError, match="spec_k"):
        BatchDecodeEngine(target, max_slots=2, draft=draft_weak, spec_k=0)


def test_tpot_divides_by_tokens_after_first_sync():
    """The multi-token honesty fix: TPOT must divide by tokens that
    arrived AFTER _t_first; the default (_n_at_first == 1) is
    bit-identical to the old one-token-per-step accounting."""
    r = GenerationResult()
    r._t_admit = r._t_submit
    r._t_first = r._t_submit + 1.0
    r._t_done = r._t_submit + 11.0
    r._n_new = 11
    assert r.slo()["tpot_s"] == pytest.approx(1.0)       # (11-1) tokens
    r._n_at_first = 6       # a speculative burst landed at the first sync
    assert r.slo()["tpot_s"] == pytest.approx(2.0)       # (11-6) tokens
    r._n_at_first = 11
    assert r.slo()["tpot_s"] is None                     # nothing after


# -- token exactness ---------------------------------------------------------

def test_spec_greedy_token_exact_weak_draft(spec_engine, target, workload):
    """Heavy-rejection parity: an independent random draft proposes,
    almost everything rolls back, and the emitted stream must STILL be
    token-for-token the non-speculative greedy output — acceptance only
    filters which step emits what, never what is emitted."""
    futs = _submit_all(spec_engine, workload)
    outs = [f.result(300) for f in futs]
    for out, ref in zip(outs, _refs(target, workload)):
        np.testing.assert_array_equal(out, ref)
    info = spec_engine.health()["spec"]
    assert info["enabled"] and info["k"] == 2
    assert info["rollbacks"] > 0, "weak draft must exercise rejection"
    assert info["proposed"] == info["target_steps"] * 2
    # accepted counts are stamped on the result futures at retirement
    assert all(getattr(f, "_spec_steps", 0) > 0 for f in futs)
    assert all(hasattr(f, "_spec_accepted") for f in futs)


def test_spec_full_accept_multiplies_tokens_per_step(target, workload):
    """Self-draft (draft == target) accepts every proposal: parity must
    hold through the full-accept path (which leaves the draft cache one
    position behind — the 2-token catch-up window repairs it) and each
    target weight-read must yield > 1 token."""
    with ServingEngine(target, max_batch_size=2, decode_chunk=6,
                       kv_page_size=16, draft=target, spec_k=2) as eng:
        futs = _submit_all(eng, workload[:4])
        outs = [f.result(300) for f in futs]
        info = eng.health()["spec"]
    for out, ref in zip(outs, _refs(target, workload[:4])):
        np.testing.assert_array_equal(out, ref)
    assert info["acceptance_rate"] == 1.0
    assert info["rollbacks"] == 0
    assert info["tokens_per_target_step"] > 1.5
    assert info["accept_run_p50"] == 2


def test_spec_rejects_sampled_requests(spec_engine):
    with pytest.raises(RequestValidationError, match="temperature"):
        spec_engine.submit(np.arange(5, dtype=np.int32), max_new_tokens=4,
                           temperature=0.8)


# -- rollback page accounting ------------------------------------------------

def test_spec_rollback_leaves_zero_leaked_pages(spec_engine, workload):
    """After a rejection-heavy serve (including prefix hits), every
    speculated page is back: pool.used equals exactly the refcount-0
    cached prefix pages, and no prefix entry holds a live ref."""
    futs = _submit_all(spec_engine, workload)
    for f in futs:
        f.result(300)
    eng = spec_engine._engine
    kv = eng.kv_stats()
    assert kv["pages_used"] == kv["prefix"]["cached_pages"]
    assert all(e.refcount == 0 for e in eng.prefix._entries.values())
    assert all(not pages for pages in eng._slot_pages)


def test_spec_cancel_mid_speculation_returns_pages(spec_engine):
    """A cancelled in-flight request's slot releases its reservation on
    the next scheduler sweep — the PR 2 cancellation seam composed with
    speculation."""
    eng = spec_engine._engine
    base_used = eng.pool.used
    rng = np.random.default_rng(9)
    f = spec_engine.submit(rng.integers(0, 128, (12,)).astype(np.int32),
                           max_new_tokens=60)
    deadline = time.time() + 30
    while time.time() < deadline and eng.busy_slots() == 0:
        time.sleep(0.005)
    assert eng.busy_slots() == 1
    f.cancel()
    with pytest.raises(RequestCancelledError):
        f.result(30)
    deadline = time.time() + 30
    while time.time() < deadline and (eng.busy_slots() or
                                      eng.pool.used > base_used):
        time.sleep(0.005)
    assert eng.busy_slots() == 0
    assert eng.pool.used <= base_used


# -- compile plan / warmup / bundles -----------------------------------------

def test_spec_plan_warmup_and_bundle_roundtrip(tmp_path, spec_engine,
                                               target, draft_weak,
                                               workload):
    """draft_admit/draft_k/verify_k are first-class plan entries: warmup
    leaves a compile-free serve window, a bundle round trip loads them
    with ZERO compiles through the fingerprint gate, and a draft-model
    swap falls back loudly (draft facts are in the fingerprint)."""
    watchdog.install(threshold=3)
    eng = spec_engine._engine
    # no "decode": the spec engine routes every chunk through draft/
    # verify, so the plain chunked-decode scan (the most expensive
    # compile in the plan) must not be warmed or bundled as dead weight
    assert set(eng.compile_plan.keys()) == {
        "admit_p96", "draft_admit_p96", "draft_k2", "verify_k2"}
    eng.warmup()
    before = sum(watchdog.compile_counts().values())
    futs = _submit_all(spec_engine, workload[:3])
    outs = [f.result(300) for f in futs]
    assert sum(watchdog.compile_counts().values()) == before, \
        "speculative serve window must be compile-free after warmup"

    path = str(tmp_path / "spec_bundle")
    manifest = eng.save_serving_bundle(path)
    keys = {e["key"] for e in manifest["entries"]}
    assert {"draft_admit_p96", "draft_k2", "verify_k2"} <= keys

    eng2 = BatchDecodeEngine(target, max_slots=3, chunk=8, page_size=16,
                             draft=draft_weak, spec_k=2, bundle=path)
    assert eng2._bundle_info["loaded"] is True
    b2 = sum(watchdog.compile_counts().values())
    from paddlepaddle_tpu.inference.serving import GenerationRequest

    reqs = [GenerationRequest(p, budget, 0.0, 0, eos)
            for p, budget, eos, _ in workload[:3]]
    eng2.serve(reqs, timeout=120)
    outs2 = [np.asarray(r.result.result(5)) for r in reqs]
    assert sum(watchdog.compile_counts().values()) == b2, \
        "bundle-loaded spec programs must serve with zero compiles"
    for a, b in zip(outs, outs2):
        np.testing.assert_array_equal(a, b)

    # draft swap: arch facts differ -> fingerprint mismatch -> lazy path
    paddle.seed(21)
    eng3 = BatchDecodeEngine(target, max_slots=3, chunk=8, page_size=16,
                             draft=_llama(hidden=48), spec_k=2, bundle=path)
    assert eng3._bundle_info["loaded"] is False
    assert "spec" in eng3._bundle_info["error"]


def test_spec_warmup_with_perf_plane(target, draft_weak):
    """warmup() on a spec engine with the perf-attribution plane armed:
    draft_k/verify_k keys carry no admission bucket, so the perf capture
    must skip them (regression: KeyError 'bucket' aborted warmup) while
    still capturing the target admit program."""
    import paddlepaddle_tpu.observability as obs
    from paddlepaddle_tpu.observability import perf

    obs.reset()
    perf.enable()
    try:
        paddle.seed(11)
        eng = BatchDecodeEngine(target, max_slots=2, chunk=8, page_size=16,
                                draft=draft_weak, spec_k=2)
        info = eng.warmup()
        assert info["compiled"] == len(eng.compile_plan.keys())
        names = {r["program"] for r in perf.registry().table()}
        assert "serving.admit" in names          # target admit captured
        assert not any("draft" in n or "verify" in n for n in names)
    finally:
        perf.reset()
        perf.disable()
        obs.reset()


# -- chaos: breaker storm mid-speculation ------------------------------------

@pytest.mark.chaos
def test_chaos_decode_storm_mid_speculation(target, draft_weak):
    """A serving.decode fault storm against the SPECULATIVE engine: every
    future resolves (typed or completed), the breaker opens and recovers,
    and the failed slots' speculated pages all return to the pool."""
    from paddlepaddle_tpu.resilience import chaos

    eng = ServingEngine(target, max_batch_size=1, decode_chunk=6,
                        kv_page_size=16, draft=draft_weak, spec_k=2,
                        breaker_threshold=2, breaker_reset_s=0.2)
    rng = np.random.default_rng(5)
    p = rng.integers(0, 128, (8,)).astype(np.int32)
    try:
        ref = eng.submit(p, max_new_tokens=4).result(300)  # warm compiles
        chaos.configure("serving.decode:exc:x2",
                        seed=int(os.environ.get("PADDLE_CHAOS_SEED",
                                                "1234")))
        failed = [eng.submit(rng.integers(0, 128, (8,)).astype(np.int32),
                             max_new_tokens=4) for _ in range(2)]
        for f in failed:
            with pytest.raises(chaos.ChaosError):
                f.result(120)
        # the loop fails the futures BEFORE reset_slots() returns the
        # pages — poll briefly instead of racing it
        deadline = time.time() + 10
        while time.time() < deadline and eng._engine.pool.used:
            time.sleep(0.01)
        assert eng._engine.pool.used == 0, \
            "failed speculation must return every page"
        time.sleep(0.25)                  # storm exhausted + reset window
        out = eng.submit(p, max_new_tokens=4).result(120)
        np.testing.assert_array_equal(out, ref)   # still token-exact
        assert eng._engine.pool.used == 0
    finally:
        chaos.disable()
        eng.stop()


# -- slow tier: int8 draft + k sweep -----------------------------------------

@pytest.mark.slow
def test_spec_int8_draft_token_exact(target, draft_weak, workload):
    """Weight-only int8 DRAFT (the draft's weight reads are the
    speculation overhead): parity is structural — acceptance filters,
    the emitted tokens are always target-greedy."""
    with ServingEngine(target, max_batch_size=3, decode_chunk=8,
                       kv_page_size=16, draft=draft_weak, spec_k=2,
                       draft_quant="weight_only_int8") as eng:
        futs = _submit_all(eng, workload)
        outs = [f.result(300) for f in futs]
        assert eng.health()["spec"]["draft"]["quant"] == "weight_only_int8"
    for out, ref in zip(outs, _refs(target, workload)):
        np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 4])
def test_spec_k_sweep_token_exact(target, draft_weak, workload, k):
    with ServingEngine(target, max_batch_size=3, decode_chunk=8,
                       kv_page_size=16, draft=draft_weak, spec_k=k) as eng:
        futs = _submit_all(eng, workload)
        outs = [f.result(300) for f in futs]
    for out, ref in zip(outs, _refs(target, workload)):
        np.testing.assert_array_equal(out, ref)
