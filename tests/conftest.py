"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
import so sharding/mesh tests run without TPU hardware (the analogue of the
reference's fake_cpu_device plugin used in test/custom_runtime/)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # must override any ambient TPU platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# The container's sitecustomize may have already imported jax and registered a
# real TPU backend; env alone is then too late. Re-point the config at CPU —
# this is honored as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddlepaddle_tpu as paddle

    paddle.seed(2024)
    np.random.seed(2024)
    yield


@pytest.fixture(autouse=True)
def _per_test_timeout():
    """Per-test wall-clock watchdog (the reference pins per-test TIMEOUT
    labels in CMake, test/collective/CMakeLists.txt:1-4): a hung collective
    or runaway compile fails THAT test instead of stalling the whole run."""
    import signal

    seconds = int(os.environ.get("PADDLE_TPU_TEST_TIMEOUT", "300"))
    armed = seconds > 0 and hasattr(signal, "SIGALRM")

    def _on_timeout(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s watchdog "
                           f"(PADDLE_TPU_TEST_TIMEOUT to adjust)")

    old = signal.signal(signal.SIGALRM, _on_timeout) if armed else None
    if armed:
        signal.alarm(seconds)
    try:
        yield
    finally:
        if armed:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
