"""Detection ops closed this round — yolo_box / yolo_loss / deform_conv2d —
checked against independent numpy loop oracles implementing the reference
kernel semantics (phi/kernels/cpu/{yolo_box,yolo_loss}_kernel.cc,
phi/kernels/funcs/deformable_conv_functor.cc)."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.nn.functional as F
from paddlepaddle_tpu.vision import ops as vops

rng = np.random.default_rng(7)


def _sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


# ---------------------------------------------------------------- yolo_box

def _yolo_box_np(x, img_size, anchors, class_num, conf_thresh, downsample,
                 clip_bbox, scale_x_y, iou_aware, iou_aware_factor):
    n, _, h, w = x.shape
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    an_num = an.shape[0]
    bias = -0.5 * (scale_x_y - 1.0)
    boxes = np.zeros((n, an_num * h * w, 4), np.float32)
    scores = np.zeros((n, an_num * h * w, class_num), np.float32)
    if iou_aware:
        iou_t, box_t = x[:, :an_num], x[:, an_num:]
    else:
        iou_t, box_t = None, x
    box_t = box_t.reshape(n, an_num, 5 + class_num, h, w)
    for i in range(n):
        img_h, img_w = float(img_size[i, 0]), float(img_size[i, 1])
        for j in range(an_num):
            for k in range(h):
                for l in range(w):
                    conf = _sigmoid(box_t[i, j, 4, k, l])
                    if iou_aware:
                        iou = _sigmoid(iou_t[i, j, k, l])
                        conf = conf ** (1 - iou_aware_factor) * \
                            iou ** iou_aware_factor
                    if conf < conf_thresh:
                        continue
                    bx = (l + _sigmoid(box_t[i, j, 0, k, l]) * scale_x_y
                          + bias) * img_w / w
                    by = (k + _sigmoid(box_t[i, j, 1, k, l]) * scale_x_y
                          + bias) * img_h / h
                    bw = np.exp(box_t[i, j, 2, k, l]) * an[j, 0] * img_w \
                        / (downsample * w)
                    bh = np.exp(box_t[i, j, 3, k, l]) * an[j, 1] * img_h \
                        / (downsample * h)
                    bi = j * h * w + k * w + l
                    bb = [bx - bw / 2, by - bh / 2, bx + bw / 2, by + bh / 2]
                    if clip_bbox:
                        bb[0] = max(bb[0], 0.0)
                        bb[1] = max(bb[1], 0.0)
                        bb[2] = min(bb[2], img_w - 1)
                        bb[3] = min(bb[3], img_h - 1)
                    boxes[i, bi] = bb
                    scores[i, bi] = conf * _sigmoid(box_t[i, j, 5:, k, l])
    return boxes, scores


@pytest.mark.parametrize("iou_aware,scale_x_y,clip",
                         [(False, 1.0, True), (True, 1.2, False)])
def test_yolo_box_vs_oracle(iou_aware, scale_x_y, clip):
    anchors = [10, 13, 16, 30]
    class_num, h, w = 3, 5, 5
    cin = len(anchors) // 2 * (5 + class_num + (1 if iou_aware else 0))
    x = rng.standard_normal((2, cin, h, w)).astype(np.float32)
    img = np.array([[80, 64], [48, 48]], np.int32)
    ref_b, ref_s = _yolo_box_np(x, img, anchors, class_num, 0.3, 8, clip,
                                scale_x_y, iou_aware, 0.5)
    b, s = vops.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img), anchors,
                         class_num, 0.3, 8, clip_bbox=clip,
                         scale_x_y=scale_x_y, iou_aware=iou_aware)
    np.testing.assert_allclose(b.numpy(), ref_b, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(s.numpy(), ref_s, rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------- yolo_loss

def _sce(x, label):
    return max(x, 0.0) - x * label + np.log1p(np.exp(-abs(x)))


def _iou_cxcywh(b1, b2):
    ow = min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2) - \
        max(b1[0] - b1[2] / 2, b2[0] - b2[2] / 2)
    oh = min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2) - \
        max(b1[1] - b1[3] / 2, b2[1] - b2[3] / 2)
    inter = 0.0 if (ow < 0 or oh < 0) else ow * oh
    return inter / (b1[2] * b1[3] + b2[2] * b2[3] - inter)


def _yolo_loss_np(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                  class_num, ignore_thresh, downsample, use_label_smooth,
                  scale_x_y):
    n, _, h, w = x.shape
    b = gt_box.shape[1]
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_num = len(anchor_mask)
    input_size = downsample * h
    bias = -0.5 * (scale_x_y - 1.0)
    xr = x.reshape(n, mask_num, 5 + class_num, h, w)
    if use_label_smooth:
        sw = min(1.0 / class_num, 1.0 / 40)
        pos, neg = 1.0 - sw, sw
    else:
        pos, neg = 1.0, 0.0
    if gt_score is None:
        gt_score = np.ones((n, b), np.float32)
    loss = np.zeros((n,), np.float64)
    obj_mask = np.zeros((n, mask_num, h, w), np.float32)
    valid = (gt_box[..., 2] >= 1e-6) & (gt_box[..., 3] >= 1e-6)

    for i in range(n):
        for j in range(mask_num):
            for k in range(h):
                for l in range(w):
                    px = (l + _sigmoid(xr[i, j, 0, k, l]) * scale_x_y
                          + bias) / w
                    py = (k + _sigmoid(xr[i, j, 1, k, l]) * scale_x_y
                          + bias) / h
                    pw = np.exp(xr[i, j, 2, k, l]) * an[anchor_mask[j], 0] \
                        / input_size
                    ph = np.exp(xr[i, j, 3, k, l]) * an[anchor_mask[j], 1] \
                        / input_size
                    best = 0.0
                    for t in range(b):
                        if not valid[i, t]:
                            continue
                        best = max(best, _iou_cxcywh(
                            (px, py, pw, ph), gt_box[i, t]))
                    if best > ignore_thresh:
                        obj_mask[i, j, k, l] = -1
        for t in range(b):
            if not valid[i, t]:
                continue
            gx, gy, gw, gh = gt_box[i, t]
            gi, gj = int(gx * w), int(gy * h)
            best_iou, best_n = 0.0, 0
            for a_idx in range(an.shape[0]):
                iou = _iou_cxcywh((0, 0, an[a_idx, 0] / input_size,
                                   an[a_idx, 1] / input_size), (0, 0, gw, gh))
                if iou > best_iou:
                    best_iou, best_n = iou, a_idx
            mask_idx = anchor_mask.index(best_n) if best_n in anchor_mask \
                else -1
            if mask_idx < 0:
                continue
            score = gt_score[i, t]
            sc = (2.0 - gw * gh) * score
            loss[i] += _sce(xr[i, mask_idx, 0, gj, gi], gx * w - gi) * sc
            loss[i] += _sce(xr[i, mask_idx, 1, gj, gi], gy * h - gj) * sc
            loss[i] += abs(xr[i, mask_idx, 2, gj, gi]
                           - np.log(gw * input_size / an[best_n, 0])) * sc
            loss[i] += abs(xr[i, mask_idx, 3, gj, gi]
                           - np.log(gh * input_size / an[best_n, 1])) * sc
            obj_mask[i, mask_idx, gj, gi] = score
            for c in range(class_num):
                loss[i] += _sce(xr[i, mask_idx, 5 + c, gj, gi],
                                pos if c == gt_label[i, t] else neg) * score
        for j in range(mask_num):
            for k in range(h):
                for l in range(w):
                    o = obj_mask[i, j, k, l]
                    if o > 1e-5:
                        loss[i] += _sce(xr[i, j, 4, k, l], 1.0) * o
                    elif o > -0.5:
                        loss[i] += _sce(xr[i, j, 4, k, l], 0.0)
    return loss.astype(np.float32)


@pytest.mark.parametrize("use_smooth,scale_x_y,with_score",
                         [(True, 1.0, False), (False, 1.1, True)])
def test_yolo_loss_vs_oracle(use_smooth, scale_x_y, with_score):
    anchors = [10, 13, 16, 30, 33, 23]
    anchor_mask = [0, 1]
    class_num, h, w, b = 4, 6, 6, 5
    n = 2
    x = rng.standard_normal(
        (n, len(anchor_mask) * (5 + class_num), h, w)).astype(np.float32)
    gt_box = rng.uniform(0.05, 0.9, (n, b, 4)).astype(np.float32)
    gt_box[:, :, 2:] *= 0.4
    gt_box[0, 3] = 0.0                      # invalid gt (w,h = 0)
    gt_label = rng.integers(0, class_num, (n, b)).astype(np.int32)
    gt_score = rng.uniform(0.3, 1.0, (n, b)).astype(np.float32) \
        if with_score else None
    ref = _yolo_loss_np(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                        class_num, 0.5, 8, use_smooth, scale_x_y)
    out = vops.yolo_loss(
        paddle.to_tensor(x), paddle.to_tensor(gt_box),
        paddle.to_tensor(gt_label), anchors, anchor_mask, class_num, 0.5, 8,
        gt_score=None if gt_score is None else paddle.to_tensor(gt_score),
        use_label_smooth=use_smooth, scale_x_y=scale_x_y)
    assert out.shape == [n]
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_yolo_loss_duplicate_cell_last_writer_wins():
    # two gts land in the same cell with the same best anchor: the second
    # write must own the objectness target (C kernel iterates t in order)
    anchors = [10, 13]
    x = np.zeros((1, 1 * 9, 4, 4), np.float32)
    gt_box = np.array([[[0.3, 0.3, 0.2, 0.2], [0.31, 0.31, 0.2, 0.2]]],
                      np.float32)
    gt_label = np.zeros((1, 2), np.int32)
    gt_score = np.array([[0.4, 0.9]], np.float32)
    ref = _yolo_loss_np(x, gt_box, gt_label, gt_score, anchors, [0], 4,
                        0.7, 8, True, 1.0)
    out = vops.yolo_loss(paddle.to_tensor(x), paddle.to_tensor(gt_box),
                         paddle.to_tensor(gt_label), anchors, [0], 4, 0.7, 8,
                         gt_score=paddle.to_tensor(gt_score))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)


# ----------------------------------------------------------- deform_conv2d

def _deform_conv_np(x, offset, weight, bias, stride, padding, dilation,
                    dg, groups, mask):
    n, cin, H, W = x.shape
    cout, cpg, kh, kw = weight.shape
    sh, sw = stride
    ph, pw = padding
    dh, dw = dilation
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    off = offset.reshape(n, dg, kh * kw, 2, Ho, Wo)
    msk = None if mask is None else mask.reshape(n, dg, kh * kw, Ho, Wo)
    out = np.zeros((n, cout, Ho, Wo), np.float64)
    cpdg = cin // dg

    def bilinear(img, h, w):
        hl, wl = int(np.floor(h)), int(np.floor(w))
        val = 0.0
        for dhi, dwi in ((0, 0), (0, 1), (1, 0), (1, 1)):
            hh, ww = hl + dhi, wl + dwi
            if 0 <= hh < img.shape[0] and 0 <= ww < img.shape[1]:
                cw = (1 - abs(h - hh)) * (1 - abs(w - ww))
                val += cw * img[hh, ww]
        return val

    for b_i in range(n):
        for ho in range(Ho):
            for wo in range(Wo):
                for oc in range(cout):
                    g = oc // (cout // groups)
                    acc = 0.0
                    for icg in range(cpg):
                        ic = g * cpg + icg
                        dgi = ic // cpdg
                        for i in range(kh):
                            for j in range(kw):
                                t = i * kw + j
                                h_im = ho * sh - ph + i * dh \
                                    + off[b_i, dgi, t, 0, ho, wo]
                                w_im = wo * sw - pw + j * dw \
                                    + off[b_i, dgi, t, 1, ho, wo]
                                v = 0.0
                                if -1 < h_im < H and -1 < w_im < W:
                                    v = bilinear(x[b_i, ic], h_im, w_im)
                                if msk is not None:
                                    v *= msk[b_i, dgi, t, ho, wo]
                                acc += v * weight[oc, icg, i, j]
                    out[b_i, oc, ho, wo] = acc
                    if bias is not None:
                        out[b_i, oc, ho, wo] += bias[oc]
    return out.astype(np.float32)


def test_deform_conv2d_zero_offset_matches_conv2d():
    x = rng.standard_normal((2, 4, 7, 7)).astype(np.float32)
    w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    off = np.zeros((2, 2 * 9, 4, 4), np.float32)
    got = vops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                             paddle.to_tensor(w), paddle.to_tensor(b),
                             stride=2, padding=1)
    want = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                    paddle.to_tensor(b), stride=2, padding=1)
    np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.parametrize("dg,groups,with_mask", [(1, 1, False), (2, 2, True)])
def test_deform_conv2d_vs_oracle(dg, groups, with_mask):
    n, cin, H, W = 2, 4, 6, 5
    cout, kh, kw = 4, 3, 2
    stride, padding, dilation = (2, 1), (1, 0), (1, 2)
    Ho = (H + 2 * padding[0] - (dilation[0] * (kh - 1) + 1)) // stride[0] + 1
    Wo = (W + 2 * padding[1] - (dilation[1] * (kw - 1) + 1)) // stride[1] + 1
    x = rng.standard_normal((n, cin, H, W)).astype(np.float32)
    w = rng.standard_normal((cout, cin // groups, kh, kw)).astype(np.float32)
    off = (2.5 * rng.standard_normal((n, 2 * dg * kh * kw, Ho, Wo))) \
        .astype(np.float32)
    mask = rng.uniform(0, 1, (n, dg * kh * kw, Ho, Wo)).astype(np.float32) \
        if with_mask else None
    ref = _deform_conv_np(x, off, w, None, stride, padding, dilation, dg,
                          groups, mask)
    got = vops.deform_conv2d(
        paddle.to_tensor(x), paddle.to_tensor(off), paddle.to_tensor(w),
        stride=stride, padding=padding, dilation=dilation,
        deformable_groups=dg, groups=groups,
        mask=None if mask is None else paddle.to_tensor(mask))
    assert got.shape == [n, cout, Ho, Wo]
    np.testing.assert_allclose(got.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_deform_conv2d_layer_and_grads():
    layer = vops.DeformConv2D(4, 6, 3, padding=1, deformable_groups=2)
    x = paddle.to_tensor(rng.standard_normal((1, 4, 5, 5)).astype(np.float32),
                         stop_gradient=False)
    off = paddle.to_tensor(
        0.5 * rng.standard_normal((1, 2 * 2 * 9, 5, 5)).astype(np.float32),
        stop_gradient=False)
    mask = paddle.to_tensor(
        rng.uniform(0, 1, (1, 2 * 9, 5, 5)).astype(np.float32))
    out = layer(x, off, mask)
    assert out.shape == [1, 6, 5, 5]
    loss = out.sum()
    loss.backward()
    for g in (x.grad, off.grad, layer.weight.grad):
        assert g is not None and np.isfinite(g.numpy()).all()
    assert float(np.abs(off.grad.numpy()).sum()) > 0  # sampling grads flow
