"""Cross-host eager collectives: 2 real processes over the TCPStore.

Reference: paddle/phi/core/distributed/collective/process_group.h:48 —
eager all_reduce/broadcast/all_gather/send/recv on a multi-process group.
Here two OS processes rendezvous through the (native C++ or python) store
and must produce identical, correct collective results.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["REPO_DIR"])
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.distributed as dist

rank = int(os.environ["PADDLE_TRAINER_ID"])

# all_reduce (sum): in-place on the tensor
t = paddle.to_tensor(np.asarray([1.0 + rank, 2.0 * (rank + 1)], np.float32))
dist.all_reduce(t)
np.testing.assert_allclose(t.numpy(), [3.0, 6.0])

# all_reduce max
t = paddle.to_tensor(np.asarray([float(rank)], np.float32))
dist.all_reduce(t, op=dist.ReduceOp.MAX)
np.testing.assert_allclose(t.numpy(), [1.0])

# broadcast from rank 0
t = paddle.to_tensor(np.full((3,), float(rank), np.float32))
dist.broadcast(t, src=0)
np.testing.assert_allclose(t.numpy(), [0.0, 0.0, 0.0])

# all_gather
outs = []
dist.all_gather(outs, paddle.to_tensor(np.asarray([rank], np.int64)))
assert [int(o.numpy()[0]) for o in outs] == [0, 1]

# all_gather_object
objs = []
dist.all_gather_object(objs, {"rank": rank})
assert [o["rank"] for o in objs] == [0, 1]

# send / recv ping-pong
if rank == 0:
    dist.send(paddle.to_tensor(np.asarray([42.0], np.float32)), dst=1)
else:
    t = paddle.to_tensor(np.zeros((1,), np.float32))
    dist.recv(t, src=0)
    np.testing.assert_allclose(t.numpy(), [42.0])

# barrier then scatter from rank 1
dist.barrier()
parts = ([paddle.to_tensor(np.asarray([10.0], np.float32)),
          paddle.to_tensor(np.asarray([20.0], np.float32))]
         if rank == 1 else None)
t = paddle.to_tensor(np.zeros((1,), np.float32))
dist.scatter(t, parts, src=1)
np.testing.assert_allclose(t.numpy(), [10.0 if rank == 0 else 20.0])

# LAP REGRESSION (round-3 advisor, high): >window same-tag collectives must
# return the CURRENT step's payload, never a window-old one. This is the
# GradScaler pattern — one tiny MAX all_reduce per step, many steps.
from paddlepaddle_tpu.distributed.host_collectives import get_host_group, _SLOT_WINDOW
import time
g = get_host_group()
steps = _SLOT_WINDOW * 2 + 5
for step in range(steps):
    if rank == 1 and step == 0:
        time.sleep(0.3)               # skew: rank 0 runs ahead into the gate
    out = g.all_reduce(np.asarray([float(step * 2 + rank)], np.float32), op="max")
    np.testing.assert_allclose(out, [float(step * 2 + 1)], err_msg=f"step {step}")

# one-sided writer lap: broadcast source posts without reading; the window
# gate must keep it bounded and every reader must see its own step's value.
for step in range(steps):
    if rank == 1 and step == 0:
        time.sleep(0.3)
    val = np.asarray([float(step)], np.float32) if rank == 0 else np.zeros(1, np.float32)
    out = g.broadcast(val, src=0)
    np.testing.assert_allclose(out, [float(step)], err_msg=f"step {step}")

# barrier must be fresh per invocation (stale bar_done regression)
for _ in range(3):
    g.barrier()

# LocalSGD: k local steps then parameter averaging across the two ranks
from paddlepaddle_tpu.distributed.fleet import LocalSGD
lin = paddle.nn.Linear(2, 1)
lin.weight.set_value(np.full((2, 1), float(rank + 1), np.float32))
lin.bias.set_value(np.zeros((1,), np.float32))
lsgd = LocalSGD(paddle.optimizer.SGD(learning_rate=0.0,
                                     parameters=lin.parameters()), k_steps=2)
xloc = paddle.to_tensor(np.ones((1, 2), np.float32))
for s in range(2):   # lr=0: weights unchanged locally; avg fires at step 2
    loss = lin(xloc).mean()
    loss.backward()
    lsgd.step()
    lsgd.clear_grad()
np.testing.assert_allclose(lin.weight.numpy(), 1.5)  # avg of 1 and 2

# batch_isend_irecv (reference: communication/batch_isend_irecv.py): each
# rank sends to the other and receives, with recv ORDERED BEFORE send in
# the op list — the batch semantics must not deadlock on list order.
send_buf = paddle.to_tensor(np.asarray([float(100 + rank)], np.float32))
recv_buf = paddle.to_tensor(np.zeros((1,), np.float32))
ops = [dist.P2POp(dist.irecv, recv_buf, 1 - rank),
       dist.P2POp(dist.isend, send_buf, 1 - rank)]
for t in dist.batch_isend_irecv(ops):
    t.wait()
np.testing.assert_allclose(recv_buf.numpy(), [float(100 + (1 - rank))])

print(f"WORKER_{rank}_OK")
"""


def test_two_process_eager_collectives(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "REPO_DIR": repo,
            "JAX_PLATFORMS": "cpu",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
        })
        env.pop("PALLAS_AXON_POOL_IPS", None)
        procs.append(subprocess.Popen([sys.executable, "-c", _WORKER],
                                      env=env, stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE, text=True))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"rank {rank} hung")
        outs.append((p.returncode, out, err))
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0 and f"WORKER_{rank}_OK" in out, (
            f"rank {rank} failed:\n{out[-1000:]}\n{err[-2000:]}")
