"""Wide OpTest sweep over ops/{math,reduction,manipulation,linalg} and the
top nn.functional surface (reference: test/legacy_test/op_test.py:418 — every
op checked against a reference forward and numeric finite-difference grads).

Three tiers per op, driven by one spec table:
  grad  — analytic tape gradient vs central differences (fp32) + a bf16
          forward execution (loose parity vs fp32),
  fwd   — forward against the numpy reference,
  smoke — executes and returns finite values (ops whose reference IS numpy's
          own implementation, or non-differentiable/int outputs).
A completeness test pins the sweep against the module surface so newly added
ops must register here.
"""

import ml_dtypes
import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from op_test import check_forward, check_grad

rng = np.random.default_rng(7)


def _f32(*shape, lo=-1.0, hi=1.0):
    return (rng.uniform(lo, hi, shape)).astype(np.float32)


def _pos(*shape, lo=0.5, hi=2.0):
    return _f32(*shape, lo=lo, hi=hi)


A23 = _f32(2, 3)
B23 = _f32(2, 3)
P23 = _pos(2, 3)
SQ = _f32(3, 3)
PD = (lambda m: (m @ m.T + 3 * np.eye(3)).astype(np.float32))(_f32(3, 3))
I23 = rng.integers(0, 3, (2, 3)).astype(np.int64)

# (op_name, tier, arrays, kwargs) — op resolved on the paddle namespace
SPECS = [
    # ---- math: smooth unary (numeric grad) --------------------------------
    ("abs", "grad", [P23], {}),
    ("acos", "grad", [_f32(2, 3, lo=-0.8, hi=0.8)], {}),
    ("acosh", "grad", [_pos(2, 3, lo=1.5, hi=3.0)], {}),
    ("asin", "grad", [_f32(2, 3, lo=-0.8, hi=0.8)], {}),
    ("asinh", "grad", [A23], {}),
    ("atan", "grad", [A23], {}),
    ("atanh", "grad", [_f32(2, 3, lo=-0.8, hi=0.8)], {}),
    ("ceil", "smoke", [A23], {}),
    ("clip", "grad", [_f32(2, 3, lo=-2, hi=2)], {"min": -0.5, "max": 0.5}),
    ("cos", "grad", [A23], {}),
    ("cosh", "grad", [A23], {}),
    ("deg2rad", "grad", [A23], {}),
    ("digamma", "grad", [_pos(2, 3, lo=1.0, hi=3.0)], {}),
    ("erf", "grad", [A23], {}),
    ("erfinv", "grad", [_f32(2, 3, lo=-0.7, hi=0.7)], {}),
    ("exp", "grad", [A23], {}),
    ("expm1", "grad", [A23], {}),
    ("floor", "smoke", [A23], {}),
    ("frac", "smoke", [P23], {}),
    ("lgamma", "grad", [_pos(2, 3, lo=1.0, hi=3.0)], {}),
    ("log", "grad", [P23], {}),
    ("log10", "grad", [P23], {}),
    ("log1p", "grad", [P23], {}),
    ("log2", "grad", [P23], {}),
    ("logit", "grad", [_f32(2, 3, lo=0.2, hi=0.8)], {}),
    ("neg", "grad", [A23], {}),
    ("rad2deg", "grad", [A23], {}),
    ("reciprocal", "grad", [P23], {}),
    ("round", "smoke", [A23], {}),
    ("rsqrt", "grad", [P23], {}),
    ("sigmoid", "grad", [A23], {}),
    ("sign", "smoke", [A23], {}),
    ("sin", "grad", [A23], {}),
    ("sinh", "grad", [A23], {}),
    ("sqrt", "grad", [P23], {}),
    ("square", "grad", [A23], {}),
    ("stanh", "grad", [A23], {}),
    ("tan", "grad", [_f32(2, 3, lo=-0.8, hi=0.8)], {}),
    ("tanh", "grad", [A23], {}),
    ("trunc", "smoke", [A23], {}),
    ("i0", "grad", [A23], {}),
    ("i0e", "smoke", [A23], {}),
    ("i1", "smoke", [A23], {}),
    ("i1e", "smoke", [A23], {}),
    ("gammaln", "grad", [_pos(2, 3, lo=1.0, hi=3.0)], {}),
    ("angle", "smoke", [A23], {}),
    ("conj", "smoke", [A23], {}),
    ("real", "smoke", [A23], {}),
    ("imag", "smoke", [A23], {}),
    ("isfinite", "smoke", [A23], {}),
    ("isinf", "smoke", [A23], {}),
    ("isnan", "smoke", [A23], {}),
    ("isneginf", "smoke", [A23], {}),
    ("isposinf", "smoke", [A23], {}),
    ("isreal", "smoke", [A23], {}),
    ("exponent", "smoke", [P23], {}),
    ("nan_to_num", "smoke", [A23], {}),
    ("logsigmoid", "grad", [A23], {}),
    # ---- math: binary ------------------------------------------------------
    ("add", "grad", [A23, B23], {}),
    ("subtract", "grad", [A23, B23], {}),
    ("multiply", "grad", [A23, B23], {}),
    ("divide", "grad", [A23, P23], {}),
    ("pow", "grad", [P23, _pos(2, 3, lo=1.0, hi=2.0)], {}),
    ("maximum", "grad", [A23, B23], {}),
    ("minimum", "grad", [A23, B23], {}),
    ("fmax", "smoke", [A23, B23], {}),
    ("fmin", "smoke", [A23, B23], {}),
    ("atan2", "grad", [A23, P23], {}),
    ("logaddexp", "grad", [A23, B23], {}),
    ("copysign", "smoke", [A23, B23], {}),
    ("heaviside", "smoke", [A23, B23], {}),
    ("hypot", "grad", [P23, _pos(2, 3)], {}),
    ("ldexp", "smoke", [A23, I23.astype(np.float32)], {}),
    ("nextafter", "smoke", [A23, B23], {}),
    ("fmod", "smoke", [A23, P23], {}),
    ("mod", "smoke", [A23, P23], {}),
    ("remainder", "smoke", [A23, P23], {}),
    ("floor_divide", "smoke", [A23, P23], {}),
    ("floor_mod", "smoke", [A23, P23], {}),
    ("gcd", "smoke", [I23, I23 + 1], {}),
    ("lcm", "smoke", [I23 + 1, I23 + 2], {}),
    ("kron", "smoke", [A23, B23], {}),
    ("inner", "grad", [A23, B23], {}),
    ("outer", "grad", [_f32(3), _f32(4)], {}),
    ("lerp", "grad", [A23, B23, _pos(2, 3, lo=0.1, hi=0.9)], {}),
    ("scale", "grad", [A23], {"scale": 2.5, "bias": 0.5}),
    ("cumsum", "grad", [A23], {"axis": 1}),
    ("cumprod", "grad", [P23], {"dim": 1}),
    ("cummax", "smoke", [A23], {"axis": 1}),
    ("cummin", "smoke", [A23], {"axis": 1}),
    ("logcumsumexp", "grad", [A23], {"axis": 1}),
    ("diff", "grad", [_f32(2, 4)], {}),
    ("trace", "grad", [SQ], {}),
    # ---- reduction ---------------------------------------------------------
    ("sum", "grad", [A23], {}),
    ("mean", "grad", [A23], {}),
    ("prod", "grad", [P23], {}),
    ("max", "grad", [A23], {}),
    ("min", "grad", [A23], {}),
    ("amax", "smoke", [A23], {}),
    ("amin", "smoke", [A23], {}),
    ("logsumexp", "grad", [A23], {}),
    ("std", "grad", [A23], {}),
    ("var", "grad", [A23], {}),
    ("median", "fwd_np", [_f32(5)], {}),
    ("nanmean", "grad", [A23], {}),
    ("nansum", "grad", [A23], {}),
    ("nanmedian", "smoke", [_f32(5)], {}),
    ("quantile", "smoke", [_f32(5)], {"q": 0.5}),
    ("nanquantile", "smoke", [_f32(5)], {"q": 0.5}),
    ("count_nonzero", "smoke", [I23], {}),
    ("all", "smoke", [I23 > 0], {}),
    ("any", "smoke", [I23 > 0], {}),
    # ---- manipulation ------------------------------------------------------
    ("reshape", "grad", [A23], {"shape": [3, 2]}),
    ("transpose", "grad", [A23], {"perm": [1, 0]}),
    ("concat", "smoke", [[A23, B23]], {}),
    ("stack", "smoke", [[A23, B23]], {}),
    ("split", "smoke", [_f32(4, 3)], {"num_or_sections": 2}),
    ("chunk", "smoke", [_f32(4, 3)], {"chunks": 2}),
    ("squeeze", "grad", [_f32(2, 1, 3)], {}),
    ("unsqueeze", "grad", [A23], {"axis": 0}),
    ("flip", "grad", [A23], {"axis": 0}),
    ("roll", "grad", [A23], {"shifts": 1}),
    ("tile", "grad", [A23], {"repeat_times": [2, 1]}),
    ("expand", "grad", [_f32(1, 3)], {"shape": [2, 3]}),
    ("broadcast_to", "grad", [_f32(1, 3)], {"shape": [2, 3]}),
    ("flatten", "grad", [_f32(2, 2, 3)], {}),
    ("gather", "smoke", [A23, np.array([1, 0], np.int64)], {}),
    ("index_select", "smoke", [A23, np.array([1, 0], np.int64)], {}),
    ("take_along_axis", "smoke", [A23, np.array([[0, 1, 0]], np.int64)], {"axis": 0}),
    ("masked_select", "smoke", [A23, A23 > 0], {}),
    ("masked_fill", "smoke", [A23, A23 > 0, 0.0], {}),
    ("where", "smoke", [A23 > 0, A23, B23], {}),
    ("diagonal", "grad", [SQ], {}),
    ("diag_embed", "smoke", [_f32(3)], {}),
    ("moveaxis", "grad", [_f32(2, 3, 4)], {"source": 0, "destination": 2}),
    ("swapaxes", "grad", [A23], {"axis0": 0, "axis1": 1}),
    ("t", "grad", [A23], {}),
    ("rot90", "smoke", [A23], {}),
    ("unbind", "smoke", [A23], {}),
    ("unique", "smoke", [I23.astype(np.float32)], {}),
    ("unique_consecutive", "smoke", [np.sort(I23.ravel()).astype(np.float32)], {}),
    ("one_hot", "smoke", [I23], {"num_classes": 4}),
    ("bincount", "smoke", [I23.ravel()], {}),
    ("histogram", "smoke", [A23], {}),
    ("pad", "grad", [A23], {"pad": [1, 1, 0, 0]}),
    ("repeat_interleave", "smoke", [A23, 2], {}),
    ("index_sample", "smoke", [A23, np.array([[0, 1], [2, 0]], np.int64)], {}),
    ("as_strided", "smoke", [_f32(6)], {"shape": [2, 3], "stride": [3, 1]}),
    ("cast", "smoke", [A23], {"dtype": "float64"}),
    ("numel", "smoke", [A23], {}),
    ("shard_index", "smoke", [I23], {"index_num": 6, "nshards": 2, "shard_id": 0}),
    ("put_along_axis", "smoke",
     [A23, np.array([[0, 1, 0]], np.int64), _f32(1, 3)], {"axis": 0}),
    ("index_add", "smoke",
     [A23, np.array([0, 1], np.int64), 0, _f32(2, 3)], {}),
    ("scatter", "smoke",
     [A23, np.array([0, 1], np.int64), _f32(2, 3)], {}),
    ("gather_nd", "smoke", [A23, np.array([[0, 1], [1, 2]], np.int64)], {}),
    ("tensordot", "grad", [A23, _f32(3, 2)], {"axes": 1}),
    ("broadcast_shape", "smoke_fn", [[2, 1], [1, 3]], {}),
    # ---- linalg ------------------------------------------------------------
    ("matmul", "grad", [A23, _f32(3, 2)], {}),
    ("bmm", "grad", [_f32(2, 2, 3), _f32(2, 3, 2)], {}),
    ("mm", "grad", [A23, _f32(3, 2)], {}),
    ("mv", "grad", [A23, _f32(3)], {}),
    ("dot", "grad", [_f32(3), _f32(3)], {}),
    ("addmm", "grad", [_f32(2, 2), A23, _f32(3, 2)], {}),
    ("einsum", "smoke_fn", ["ij,jk->ik", A23, _f32(3, 2)], {}),
    ("norm", "grad", [P23], {}),
    ("vector_norm", "grad", [_f32(4)], {}),
    ("matrix_norm", "smoke", [SQ], {}),
    ("det", "grad", [PD], {}),
    ("slogdet", "smoke", [PD], {}),
    ("inv", "grad", [PD], {}),
    ("inverse", "smoke", [PD], {}),
    ("solve", "grad", [PD, _f32(3)], {}),
    ("cholesky", "grad", [PD], {}),
    ("cholesky_solve", "smoke",
     [_f32(3, 1), np.linalg.cholesky(PD).astype(np.float32)], {}),
    ("triangular_solve", "smoke",
     [np.triu(PD).astype(np.float32), _f32(3, 1)], {}),
    ("matrix_power", "smoke", [SQ], {"n": 2}),
    ("matrix_exp", "grad", [(SQ * 0.3).astype(np.float32)], {}),
    ("fp8_fp8_half_gemm_fused", "smoke",
     [A23.astype(ml_dtypes.float8_e4m3fn),
      B23.T.astype(ml_dtypes.float8_e4m3fn)], {}),
    ("multi_dot", "smoke", [[A23, _f32(3, 2)]], {}),
    ("qr", "smoke", [A23], {}),
    ("svd", "smoke", [A23], {}),
    ("svdvals", "smoke", [A23], {}),
    ("eig", "smoke", [PD], {}),
    ("eigh", "smoke", [PD], {}),
    ("eigvals", "smoke", [PD], {}),
    ("eigvalsh", "smoke", [PD], {}),
    ("lu", "smoke", [PD], {}),
    ("lstsq", "smoke", [A23, _f32(2, 1)], {}),
    ("pinv", "smoke", [A23], {}),
    ("matrix_rank", "smoke", [SQ], {}),
    ("cross", "grad", [_f32(2, 3), _f32(2, 3)], {}),
    ("cdist", "grad", [_f32(2, 3), _f32(4, 3)], {}),
    ("dist", "grad", [A23, B23], {}),
    ("cov", "smoke", [A23], {}),
    ("corrcoef", "smoke", [A23], {}),
    ("householder_product", "smoke", [_f32(3, 2), _f32(2)], {}),
]

# top nn.functional entries (reference python/paddle/nn/functional surface)
NF_SPECS = [
    ("relu", "grad", [A23], {}),
    ("gelu", "grad", [A23], {}),
    ("silu", "grad", [A23], {}),
    ("softmax", "grad", [A23], {}),
    ("log_softmax", "grad", [A23], {}),
    ("sigmoid", "grad", [A23], {}),
    ("tanh", "grad", [A23], {}),
    ("elu", "grad", [A23], {}),
    ("leaky_relu", "grad", [A23], {}),
    ("hardswish", "grad", [_f32(2, 3, lo=-2.5, hi=2.5)], {}),
    ("hardsigmoid", "grad", [A23], {}),
    ("hardtanh", "grad", [_f32(2, 3, lo=-2, hi=2)], {}),
    ("mish", "grad", [A23], {}),
    ("softplus", "grad", [A23], {}),
    ("softsign", "grad", [A23], {}),
    ("selu", "grad", [A23], {}),
    ("celu", "grad", [A23], {}),
    ("relu6", "grad", [_f32(2, 3, lo=-2, hi=8)], {}),
    ("swish", "grad", [A23], {}),
    ("tanhshrink", "grad", [A23], {}),
    ("softshrink", "grad", [_f32(2, 3, lo=1.0, hi=2.0)], {}),
    ("hardshrink", "grad", [_f32(2, 3, lo=1.0, hi=2.0)], {}),
    ("prelu", "grad", [A23, np.array([0.25], np.float32)], {}),
    ("normalize", "grad", [P23], {}),
    ("dropout", "smoke", [A23], {"p": 0.0}),
    ("linear", "grad", [A23, _f32(3, 4), _f32(4)], {}),
    ("mse_loss", "grad", [A23, B23], {}),
    ("l1_loss", "smoke", [A23, B23], {}),
    ("smooth_l1_loss", "grad", [A23, B23], {}),
    ("kl_div", "grad", [np.log(_pos(2, 3, lo=0.2, hi=0.8)), _pos(2, 3, lo=0.2, hi=0.8)], {}),
    ("binary_cross_entropy", "grad",
     [_f32(2, 3, lo=0.2, hi=0.8), (_f32(2, 3) > 0).astype(np.float32)], {}),
    ("binary_cross_entropy_with_logits", "grad",
     [A23, (_f32(2, 3) > 0).astype(np.float32)], {}),
    ("log_loss", "grad",
     [_f32(2, 1, lo=0.2, hi=0.8), (_f32(2, 1) > 0).astype(np.float32)], {}),
    ("square_error_cost", "grad", [A23, B23], {}),
    ("cosine_similarity", "grad", [P23, _pos(2, 3)], {}),
    ("pairwise_distance", "grad", [A23, B23], {}),
    ("glu", "grad", [_f32(2, 4)], {}),
    ("embedding", "smoke", [I23, _f32(5, 4)], {}),
    ("pixel_shuffle", "smoke", [_f32(1, 4, 2, 2)], {"upscale_factor": 2}),
    ("unfold", "smoke", [_f32(1, 2, 4, 4)], {"kernel_sizes": 2}),
    ("interpolate", "smoke", [_f32(1, 2, 4, 4)], {"scale_factor": 2}),
    ("grid_sample", "smoke", [_f32(1, 1, 4, 4), _f32(1, 2, 2, 2)], {}),
    ("avg_pool2d", "grad", [_f32(1, 2, 4, 4)], {"kernel_size": 2}),
    ("max_pool2d", "grad", [_f32(1, 2, 4, 4)], {"kernel_size": 2}),
    ("adaptive_avg_pool2d", "grad", [_f32(1, 2, 4, 4)], {"output_size": 2}),
    ("adaptive_max_pool1d", "smoke", [_f32(1, 2, 6)], {"output_size": 2}),
    ("conv2d", "grad", [_f32(1, 2, 4, 4), _f32(3, 2, 2, 2)], {}),
    ("layer_norm", "grad", [A23], {"normalized_shape": 3}),
]


def _resolve(name, namespace):
    return getattr(namespace, name)


def _run_spec(fn, tier, arrays, kwargs):
    if tier == "smoke_fn":  # first arg is not a tensor
        out = fn(*arrays, **kwargs)
        return
    if tier == "smoke":
        tensors = [paddle.to_tensor(a) if isinstance(a, np.ndarray)
                   else ([paddle.to_tensor(x) for x in a]
                         if isinstance(a, list) else a)
                   for a in arrays]
        out = fn(*tensors, **kwargs)
        leaves = out if isinstance(out, (list, tuple)) else [out]
        for leaf in leaves:
            if hasattr(leaf, "numpy"):
                arr = np.asarray(leaf.numpy())
                if np.issubdtype(arr.dtype, np.floating):
                    assert np.isfinite(arr).all()
        return
    if tier == "fwd_np":
        np_fn = getattr(np, fn.__name__)
        check_forward(fn, lambda *a, **k: np_fn(*a, **k), arrays,
                      kwargs=kwargs, rtol=1e-5, atol=1e-5)
        return
    # tier == "grad": float inputs get numeric-grad checked; ints ride along
    grad_idx = [i for i, a in enumerate(arrays)
                if isinstance(a, np.ndarray) and np.issubdtype(a.dtype, np.floating)]
    check_grad(fn, arrays, grad_idx=grad_idx, kwargs=kwargs)
    # bf16 forward parity (loose): the op must run in bf16 and stay close
    bf = [a.astype("bfloat16") if (isinstance(a, np.ndarray)
                                   and np.issubdtype(a.dtype, np.floating)) else a
          for a in arrays]
    try:
        import jax.numpy as jnp

        t32 = fn(*[paddle.to_tensor(a) for a in arrays], **kwargs)
        tb = fn(*[paddle.to_tensor(a) for a in bf], **kwargs)
        o32 = np.asarray(t32.numpy(), np.float64)
        ob = np.asarray(tb.numpy().astype(np.float64))
        scale = np.maximum(np.abs(o32), 1.0)
        assert (np.abs(o32 - ob) / scale).max() < 0.1
    except AssertionError:
        raise
    except Exception:
        pass  # some ops reject bf16 inputs (CPU lapack lowering): acceptable


@pytest.mark.parametrize("name,tier,arrays,kwargs",
                         SPECS, ids=[s[0] for s in SPECS])
def test_ops_sweep(name, tier, arrays, kwargs):
    fn = _resolve(name, paddle)
    _run_spec(fn, tier, arrays, kwargs)


@pytest.mark.parametrize("name,tier,arrays,kwargs",
                         NF_SPECS, ids=[f"nf_{s[0]}" for s in NF_SPECS])
def test_nn_functional_sweep(name, tier, arrays, kwargs):
    import paddlepaddle_tpu.nn.functional as NF

    fn = _resolve(name, NF)
    _run_spec(fn, tier, arrays, kwargs)


def test_sweep_covers_op_surface():
    """Every public op in the four core modules is either in the sweep or
    explicitly waived (in-place aliases, bookkeeping helpers)."""
    from paddlepaddle_tpu.ops import linalg, manipulation, math as m, reduction

    covered = {s[0] for s in SPECS}
    waived = {
        # in-place variants alias their out-of-place op
        "abs_", "add_", "ceil_", "clip_", "cos_", "divide_", "exp_",
        "floor_", "lerp_", "multiply_", "neg_", "pow_", "reciprocal_",
        "remainder_", "reshape_", "round_", "rsqrt_", "scale_", "scatter_",
        "sin_", "sqrt_", "subtract_", "tanh_", "where_",
        # bookkeeping / non-tensor helpers
        "astype", "builtins_sum", "is_empty", "is_tensor", "rank", "shape",
        "tolist", "view", "view_as", "increment", "multiplex", "chunk_eval",
        "as_complex", "as_real", "crop", "matrix_transpose", "swapdims",
        "strided_slice", "slice", "scatter_nd", "scatter_nd_add",
        "index_put", "masked_scatter", "broadcast_tensors", "expand_as",
    }
    missing = []
    for mod in (m, reduction, manipulation, linalg):
        tail = mod.__name__.rsplit(".", 1)[-1]
        for n, f in vars(mod).items():
            if n.startswith("_") or not callable(f):
                continue
            if not getattr(f, "__module__", "").endswith(tail):
                continue
            if n not in covered and n not in waived:
                missing.append(f"{tail}.{n}")
    assert not missing, f"ops missing from the sweep: {sorted(missing)}"
