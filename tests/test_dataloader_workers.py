"""Subprocess DataLoader workers (reference: python/paddle/io/dataloader/
worker.py, reader.py:262): GIL-escaping throughput, worker_init_fn,
persistent workers, and IterableDataset self-sharding via get_worker_info.

Datasets are defined at module level so the default ``forkserver`` start
method (fork-safe under the multithreaded JAX parent) can pickle them; one
test covers the documented fallback-to-fork path for local classes.
"""

import os
import time
from pathlib import Path

import numpy as np
import pytest

from paddlepaddle_tpu.io import DataLoader, get_worker_info
from paddlepaddle_tpu.io.dataset import Dataset, IterableDataset


class _PyHeavy(Dataset):
    """Pure-python CPU-bound __getitem__ — threads serialize on the GIL,
    subprocess workers do not."""

    def __init__(self, n=24, work=60_000):
        self.n = n
        self.work = work

    def __getitem__(self, i):
        acc = 0
        for j in range(self.work):  # deliberately GIL-bound
            acc += (i * j) % 7
        return np.array([i, acc % 97], np.float32)

    def __len__(self):
        return self.n


class _ArangeDs(Dataset):
    def __init__(self, n=10):
        self.n = n

    def __getitem__(self, i):
        info = get_worker_info()
        assert info is not None and 0 <= info.id < 2
        return np.array([i], np.int64)

    def __len__(self):
        return self.n


class _PidDs(Dataset):
    def __getitem__(self, i):
        return np.array([os.getpid(), i], np.int64)

    def __len__(self):
        return 8


class _PlainDs(Dataset):
    def __init__(self, n=16):
        self.n = n

    def __getitem__(self, i):
        return np.array([i], np.int64)

    def __len__(self):
        return self.n


class _BadDs(Dataset):
    def __getitem__(self, i):
        if i == 3:
            raise RuntimeError("boom")
        return np.array([i], np.int64)

    def __len__(self):
        return 8


class _Stream(IterableDataset):
    def __iter__(self):
        info = get_worker_info()
        lo, hi = 0, 16
        if info is not None:  # reference pattern: shard by worker id
            per = (hi - lo) // info.num_workers
            lo = info.id * per
            hi = lo + per
        for i in range(lo, hi):
            yield np.array([i], np.int64)


_init_calls = []


def _init_fn(worker_id):
    _init_calls.append(worker_id)  # runs in the child (parent list stays empty)


def _time(loader):
    t0 = time.perf_counter()
    out = [b.numpy() for b in loader]
    return time.perf_counter() - t0, out


@pytest.mark.skipif(os.cpu_count() < 2, reason="needs 2 cores")
def test_subprocess_beats_threads_on_python_heavy():
    ds = _PyHeavy()
    threads = DataLoader(ds, batch_size=4, num_workers=2,
                         use_multiprocess=False, persistent_workers=True)
    procs = DataLoader(ds, batch_size=4, num_workers=2,
                       persistent_workers=True)
    # warmup epoch: child startup + interpreter/jax import can dwarf the
    # workload on a small box; persistent workers let us time steady state
    _time(threads)
    _time(procs)
    t_threads, out_t = _time(threads)
    t_procs, out_p = _time(procs)
    for a, b in zip(out_t, out_p):
        np.testing.assert_allclose(a, b)  # same batches, same order
    # GIL-bound transform: processes must actually parallelize. Retry the
    # timing once on a noise spike (same policy as the overhead gates):
    # on a contended container a single epoch's scheduling jitter can
    # briefly make 2 subprocesses lose to 2 threads
    if not t_procs < t_threads * 0.8:
        t_threads, _ = _time(threads)
        t_procs, _ = _time(procs)
    assert t_procs < t_threads * 0.8, (t_procs, t_threads)


def test_worker_init_fn_and_order():
    loader = DataLoader(_ArangeDs(), batch_size=2, num_workers=2,
                        worker_init_fn=_init_fn)
    flat = np.concatenate([b.numpy().ravel() for b in loader])
    np.testing.assert_array_equal(flat, np.arange(10))
    assert _init_calls == []  # init ran in workers, not the parent
    assert get_worker_info() is None  # main process sees None


def test_persistent_workers_reuse_pool():
    loader = DataLoader(_PidDs(), batch_size=2, num_workers=2,
                        persistent_workers=True)
    pids1 = {int(b.numpy()[0, 0]) for b in loader}
    pool1 = loader._pool
    pids2 = {int(b.numpy()[0, 0]) for b in loader}
    assert loader._pool is pool1 and pool1.alive  # same processes both epochs
    assert pids1 == pids2
    assert os.getpid() not in pids1  # loading happened in children
    loader._pool.shutdown()


def test_abandoned_epoch_does_not_leak_stale_batches():
    """Early break with persistent workers: the next epoch must start from
    batch 0, discarding leftovers of the abandoned epoch (epoch-tag filter)."""
    dl = DataLoader(_PlainDs(), batch_size=2, num_workers=2,
                    persistent_workers=True)
    it = iter(dl)
    np.testing.assert_array_equal(next(it).numpy().ravel(), [0, 1])
    del it  # abandon mid-epoch
    flat = np.concatenate([b.numpy().ravel() for b in dl])
    np.testing.assert_array_equal(flat, np.arange(16))
    dl._pool.shutdown()


def test_dead_worker_pool_is_replaced_not_hung():
    """A worker exception kills its process; a persistent pool must be torn
    down (retry gets fresh workers) instead of hanging on a dead queue."""
    dl = DataLoader(_BadDs(), batch_size=2, num_workers=2,
                    persistent_workers=True)
    with pytest.raises(RuntimeError, match="boom"):
        list(dl)
    assert dl._pool is None  # broken pool not kept for reuse


def test_iterable_dataset_self_sharding():
    loader = DataLoader(_Stream(), batch_size=2, num_workers=2)
    got = sorted(int(x) for b in loader for x in b.numpy().ravel())
    assert got == list(range(16))  # every element exactly once


def test_unpicklable_dataset_falls_back_to_fork_with_warning():
    """A dataset class defined inside a function cannot pickle for the
    default forkserver start method; the loader must warn and fall back to
    fork rather than dying in Process.start()."""
    class Local(Dataset):
        def __getitem__(self, i):
            return np.array([i], np.int64)

        def __len__(self):
            return 6

    with pytest.warns(UserWarning, match="falling back to the 'fork'"):
        loader = DataLoader(Local(), batch_size=2, num_workers=2)
        flat = np.concatenate([b.numpy().ravel() for b in loader])
    np.testing.assert_array_equal(flat, np.arange(6))


def test_explicit_spawn_with_unpicklable_dataset_raises(monkeypatch):
    class Local(Dataset):
        def __getitem__(self, i):
            return np.array([i], np.int64)

        def __len__(self):
            return 4

    monkeypatch.setenv("PADDLE_TPU_MP_START_METHOD", "spawn")
    with pytest.raises(RuntimeError, match="picklable"):
        list(DataLoader(Local(), batch_size=2, num_workers=2))


def test_explicit_fork_still_works(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_MP_START_METHOD", "fork")
    loader = DataLoader(_PlainDs(8), batch_size=2, num_workers=2)
    flat = np.concatenate([b.numpy().ravel() for b in loader])
    np.testing.assert_array_equal(flat, np.arange(8))


def test_killed_worker_raises_instead_of_hanging():
    """A worker that dies WITHOUT posting an error (SIGKILL, startup crash)
    must surface as an exception from the health poll, not a parent hang."""
    import signal

    dl = DataLoader(_PyHeavy(n=64, work=2_000_000), batch_size=2,
                    num_workers=2, persistent_workers=True)
    it = iter(dl)
    next(it)
    os.kill(dl._pool.procs[0].pid, signal.SIGKILL)
    with pytest.raises(RuntimeError, match="died unexpectedly"):
        for _ in it:
            pass
    dl._pool.shutdown()


def test_stdin_main_falls_back_to_fork():
    """A parent whose __main__ came from stdin (heredoc) cannot re-import
    it in forkserver workers; the loader must fall back to fork, warn, and
    still deliver batches (r5 verify finding)."""
    import subprocess
    import sys

    script = (
        "import sys, warnings, numpy as np\n"
        f"sys.path.insert(0, {repr(str(Path(__file__).parent))})\n"
        "from paddlepaddle_tpu.io import DataLoader\n"
        "import test_dataloader_workers as tw\n"
        "with warnings.catch_warnings(record=True) as w:\n"
        "    warnings.simplefilter('always')\n"
        "    dl = DataLoader(tw._PlainDs(6), batch_size=2, num_workers=2)\n"
        "    got = np.concatenate([b.numpy().ravel() for b in dl])\n"
        "assert got.tolist() == [0, 1, 2, 3, 4, 5], got\n"
        "assert any('falling back' in str(x.message) for x in w)\n"
        "print('OK')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=str(Path(__file__).parent.parent))
    r = subprocess.run([sys.executable, "-"], input=script, text=True,
                       capture_output=True, env=env, timeout=240)
    assert r.returncode == 0 and "OK" in r.stdout, (r.stdout, r.stderr)
