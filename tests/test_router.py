"""Serving fleet router: health-aware balancing, breaker eviction +
half-open re-admission, mid-flight failover, deadline-bounded retries,
prefix-affine routing, rolling restart, and the replica-kill chaos drill
(inference/router.py).

Most tests drive fleets of STATIC fake-model engines so the routing layer
is exercised without JAX compiles; one continuous-engine test runs the
router over two real tiny-Llama replicas. The invariant every drill
asserts: each submitted request's future resolves — completed, or failed
with a meaningful error. Zero silently-lost futures, whatever dies.
"""

import os
import threading
import time

import numpy as np
import pytest

from paddlepaddle_tpu.inference import (
    DeadlineExceededError,
    EngineDrainingError,
    FleetUnavailableError,
    ReplicaClient,
    RequestValidationError,
    ServingEngine,
    ServingError,
    ServingRouter,
)
from test_serving_robustness import FakeModel, _prompt

# a long interval keeps the prober quiet so tests drive probes explicitly
# via router._probe_once() where determinism matters
_QUIET = 60.0


def _factory(model=None, **kw):
    kw.setdefault("mode", "static")
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_wait_ms", 2.0)
    kw.setdefault("max_len", 64)
    return lambda: ServingEngine(model() if callable(model)
                                 else (model or FakeModel()), **kw)


def _resolve_all(futs, timeout=60):
    """Wait for every future; return (oks, errors) — the zero-lost-futures
    check every drill runs through."""
    oks, errs = [], []
    for f in futs:
        try:
            oks.append(f.result(timeout))
        except Exception as e:  # noqa: BLE001 — collected for assertions
            errs.append(e)
    return oks, errs


# -- balancing ---------------------------------------------------------------

def test_pick_least_estimated_wait():
    r = ServingRouter([_factory(), _factory(), _factory()],
                      probe_interval_s=_QUIET)
    r.start()
    try:
        r._probe_once()
        loaded, idle, mid = r._replicas
        loaded.snapshot = dict(loaded.snapshot, est_wait_s=2.0, ok=True)
        mid.snapshot = dict(mid.snapshot, est_wait_s=0.5, ok=True)
        idle.snapshot = dict(idle.snapshot, est_wait_s=0.0, ok=True)

        class _P:  # minimal pending shim for _pick
            tried = set()
            prefix_key = None

        assert r._pick(_P()) is idle
        # live router-side inflight breaks est-wait ties
        idle.inflight = 5
        idle.snapshot = dict(idle.snapshot, est_wait_s=0.5)
        assert r._pick(_P()) is mid
    finally:
        r.stop()


def test_traffic_spreads_and_availability_accounting():
    r = ServingRouter([_factory(FakeModel(delay_s=0.01)),
                       _factory(FakeModel(delay_s=0.01))],
                      probe_interval_s=0.05)
    try:
        futs = [r.submit(_prompt(), max_new_tokens=2) for _ in range(16)]
        oks, errs = _resolve_all(futs)
        assert len(oks) == 16 and not errs
        h = r.health()
        assert h["ok"] and h["router"]["healthy"] == 2
        assert h["router"]["submitted"] == 16
        assert h["router"]["completed"] == 16
        assert h["router"]["failed"] == 0
        assert h["router"]["picks"] == 16
        # both replicas actually served traffic (least-loaded spreads)
        assert all(rep.client.engine.stats["requests"] > 0
                   for rep in r._replicas)
    finally:
        r.stop()


# -- breaker eviction + half-open re-admission -------------------------------

def test_breaker_evicts_sick_replica_then_readmits():
    sick_model = FakeModel(fail_next=3)
    r = ServingRouter([_factory(sick_model, max_batch_size=1),
                       _factory(FakeModel(), max_batch_size=1)],
                      probe_interval_s=_QUIET, breaker_threshold=3,
                      breaker_reset_s=30.0)
    r.start()
    try:
        r._probe_once()
        sick, healthy = r._replicas
        # force traffic at the sick replica until its breaker opens: each
        # submit fails mid-flight there, fails over, and completes on the
        # healthy one — callers never see the failures
        healthy.snapshot = dict(healthy.snapshot, est_wait_s=5.0)
        served = 0
        while sick.breaker.state != "open" and served < 10:
            assert r.submit(_prompt(), max_new_tokens=2).result(30) \
                .shape == (6,)
            served += 1
        assert sick.breaker.state == "open"
        assert r.stats["evictions"] == 1
        assert r.stats["failovers"] >= 3
        assert not r.health()["replicas"]["r0"]["ok"]
        # evicted: picks avoid it entirely (failures are exhausted, so a
        # pick reaching it WOULD succeed — rotation must not send one)
        healthy.snapshot = dict(healthy.snapshot, est_wait_s=0.0)
        before = sick.client.engine.stats["requests"]
        for _ in range(4):
            r.submit(_prompt(), max_new_tokens=2).result(30)
        assert sick.client.engine.stats["requests"] == before
        # reset window passes (rewound, not slept — deterministic) -> the
        # ok health probe re-admits through half-open
        sick.breaker._opened_at -= 31.0
        r._probe_once()
        assert sick.breaker.state == "closed"
        assert r.stats["readmissions"] == 1
        assert r.health()["replicas"]["r0"]["ok"]
    finally:
        r.stop()


def test_ok_probe_does_not_clear_request_failure_streak():
    """A replica whose /healthz reads ok while its requests fail must
    still reach eviction: probes only re-admit through half-open, they
    never reset a closed breaker's failure count."""
    r = ServingRouter([_factory()], probe_interval_s=_QUIET,
                      breaker_threshold=3)
    r.start()
    try:
        rep = r._replicas[0]
        rep.breaker.record_failure()
        rep.breaker.record_failure()
        r._probe_once()                       # health ok — but 2 failures
        assert rep.breaker.consecutive_failures == 2
        rep.breaker.record_failure()          # ...so the 3rd still opens
        assert rep.breaker.state == "open"
    finally:
        r.stop()


# -- mid-flight failover -----------------------------------------------------

def test_midflight_kill_fails_over_and_preserves_results():
    r = ServingRouter([_factory(FakeModel(delay_s=0.05), max_batch_size=1),
                       _factory(FakeModel(delay_s=0.05), max_batch_size=1)],
                      probe_interval_s=0.05, breaker_reset_s=0.3)
    try:
        futs = [r.submit(_prompt(n=4, v=i), max_new_tokens=2)
                for i in range(8)]
        r._replicas[0].client.kill()          # dies holding queued work
        oks, errs = _resolve_all(futs)
        assert not errs, [type(e).__name__ for e in errs]
        # every result is the caller's own prompt + its new tokens
        for i, out in enumerate(oks):
            assert out.shape == (6,)
            assert (out[:4] == i).all()
        assert r.stats["failovers"] >= 1
        assert r.health()["router"]["completed"] == 8
    finally:
        r.stop()


def test_all_replicas_out_is_typed_fleet_unavailable():
    r = ServingRouter([_factory()], probe_interval_s=_QUIET,
                      breaker_reset_s=5.0)
    r.start()
    try:
        r._replicas[0].client.kill()
        for _ in range(3):
            r._probe_once()                   # probes evict the dead replica
        assert r._replicas[0].breaker.state == "open"
        with pytest.raises(FleetUnavailableError) as ei:
            r.submit(_prompt(), max_new_tokens=2)
        assert ei.value.replicas == 1
        assert ei.value.retry_after_s > 0     # soonest half-open window
        assert isinstance(ei.value, ServingError)
    finally:
        r.stop()


def test_validation_error_not_retried():
    """Request-shaped failures travel WITH the request: no replica can
    serve them, so they surface immediately with zero retries."""
    r = ServingRouter([_factory(max_len=16), _factory(max_len=16)],
                      probe_interval_s=_QUIET)
    try:
        with pytest.raises(RequestValidationError):
            r.submit(_prompt(14), max_new_tokens=8)
        assert r.stats["retries"] == 0
        assert r.stats["failovers"] == 0
    finally:
        r.stop()


# -- deadlines vs retries ----------------------------------------------------

def test_retries_never_pass_the_deadline():
    """All replicas failing + a generous attempt budget: the request's
    deadline bounds the whole retry dance — the future resolves (typed)
    no later than deadline + one backoff, never after."""
    from paddlepaddle_tpu.resilience.retry import RetryPolicy

    always_sick = lambda: FakeModel(fail_next=10 ** 6)  # noqa: E731
    r = ServingRouter([_factory(always_sick, max_batch_size=1),
                       _factory(always_sick, max_batch_size=1)],
                      probe_interval_s=0.05, breaker_threshold=100,
                      retry_policy=RetryPolicy(max_attempts=1000,
                                               base_delay=0.02,
                                               max_delay=0.05))
    try:
        t0 = time.monotonic()
        fut = r.submit(_prompt(), max_new_tokens=2, deadline_s=0.4)
        with pytest.raises((RuntimeError, DeadlineExceededError)):
            fut.result(30)
        wall = time.monotonic() - t0
        assert wall < 0.4 + 0.3, f"retries ran {wall:.2f}s past the deadline"
        assert fut.done()
    finally:
        r.stop()


def test_expired_deadline_rejected_at_submit():
    r = ServingRouter([_factory()], probe_interval_s=_QUIET)
    try:
        with pytest.raises(DeadlineExceededError):
            r.submit(_prompt(), max_new_tokens=2, deadline_s=0.0)
    finally:
        r.stop()


# -- prefix-affine routing ---------------------------------------------------

def test_prefix_affinity_stable_and_spread():
    r = ServingRouter([_factory(), _factory(), _factory(), _factory()],
                      probe_interval_s=_QUIET)
    r.start()
    try:
        r._probe_once()
        rng = np.random.default_rng(0)

        def route(prefix_ids):
            class _P:
                tried = set()
                prefix_key = prefix_ids.tobytes()

            return r._pick(_P()).name

        prefixes = [rng.integers(0, 1000, (16,)).astype(np.int32)
                    for _ in range(12)]
        homes = {p.tobytes(): route(p) for p in prefixes}
        # stable: the same system prompt always routes to the same replica
        for p in prefixes:
            for _ in range(3):
                assert route(p) == homes[p.tobytes()]
        # spread: 12 distinct prefixes land on more than one replica —
        # random routing would, affinity-by-hash must too (it shards the
        # prefix-cache working set instead of piling onto one replica)
        assert len(set(homes.values())) > 1
        # unhealthy preferred replica: rendezvous falls to the next choice
        p0 = prefixes[0]
        home = next(rep for rep in r._replicas if rep.name == homes[
            p0.tobytes()])
        home.in_rotation = False
        moved = route(p0)
        assert moved != home.name
        home.in_rotation = True
        assert route(p0) == home.name         # ...and back when it returns
        # saturated preferred replica: falls back to least-loaded
        home.snapshot = dict(home.snapshot,
                             est_wait_s=r.affinity_max_wait_s + 1.0)
        assert route(p0) != home.name
    finally:
        r.stop()


def test_prefix_affinity_hit_rate_beats_random():
    """End-to-end over fake engines: N requests sharing 3 system prompts
    each land on their prefix's home replica — every replica sees requests
    from at most... exactly the prefixes it owns, while random/least-loaded
    routing scatters them. (The real cache-hit-rate win is measured by
    tools/serving_bench.py --profile prefix --replicas N.)"""
    r = ServingRouter([_factory(), _factory(), _factory()],
                      probe_interval_s=0.05)
    try:
        rng = np.random.default_rng(1)
        prefixes = [rng.integers(0, 1000, (8,)).astype(np.int32)
                    for _ in range(3)]
        owners = {}
        for k, pfx in enumerate(prefixes):
            for _ in range(6):
                tail = rng.integers(0, 1000, (4,)).astype(np.int32)
                fut = r.submit(np.concatenate([pfx, tail]),
                               max_new_tokens=2, prefix_len=8)
                fut.result(30)
                # the replica that served it is the one whose inflight we
                # can't see anymore — recover it from engine request counts
            owners[k] = [rep.client.engine.stats["requests"]
                         for rep in r._replicas]
        # per-prefix deltas: each prefix's 6 requests all hit ONE replica
        prev = [0, 0, 0]
        for k in range(3):
            delta = [owners[k][i] - prev[i] for i in range(3)]
            assert sorted(delta) == [0, 0, 6], delta
            prev = owners[k]
    finally:
        r.stop()


# -- rolling restart ---------------------------------------------------------

def test_rolling_restart_drops_zero_requests():
    from paddlepaddle_tpu.resilience.retry import RetryPolicy

    r = ServingRouter([_factory(FakeModel(delay_s=0.01), max_batch_size=2),
                       _factory(FakeModel(delay_s=0.01), max_batch_size=2),
                       _factory(FakeModel(delay_s=0.01), max_batch_size=2)],
                      probe_interval_s=0.05,
                      # generous budget: a request could be drain-shed by
                      # one restarting replica and land on the next one up
                      retry_policy=RetryPolicy(max_attempts=8,
                                               base_delay=0.01,
                                               max_delay=0.05))
    r.start()
    futs, stop = [], threading.Event()
    lock = threading.Lock()

    def client():
        while not stop.is_set():
            try:
                f = r.submit(_prompt(), max_new_tokens=2)
            except ServingError:
                continue          # admission refusals are typed + visible;
            with lock:            # the drill cares about ACCEPTED requests
                futs.append(f)
            time.sleep(0.002)

    threads = [threading.Thread(target=client) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)           # traffic flowing
        res = r.rolling_restart(drain_timeout=5.0, health_timeout=10.0)
        stop.set()
        for t in threads:
            t.join(30)
        assert res["ok"], res
        assert [x["generation"] for x in res["replicas"]] == [1, 1, 1]
        with lock:
            taken = list(futs)
        assert len(taken) > 10    # the restart happened UNDER traffic
        oks, errs = _resolve_all(taken)
        assert not errs, [f"{type(e).__name__}: {e}" for e in errs[:5]]
        assert len(oks) == len(taken)       # zero dropped requests
        h = r.health()
        assert h["ok"] and h["router"]["healthy"] == 3
        assert h["router"]["rolling_restarts"] == 1
    finally:
        stop.set()
        for t in threads:
            t.join(5)
        r.stop()


def test_rolling_restart_aborts_on_unhealthy_replica():
    """A restarted replica that never turns healthy stops the rollout:
    it stays OUT of rotation and the remaining replicas keep their old
    engines — a bad deploy cannot walk the whole fleet down."""
    r = ServingRouter([_factory(), _factory()], probe_interval_s=_QUIET)
    r.start()
    try:
        broken = r._replicas[0].client
        orig_restart = broken.restart

        def bad_restart(drain_timeout=None):
            orig_restart(drain_timeout)
            broken.engine.drain(0.1)        # new engine comes up not-ok

        broken.restart = bad_restart
        res = r.rolling_restart(drain_timeout=1.0, health_timeout=0.3)
        assert not res["ok"]
        assert len(res["replicas"]) == 1    # r1 was never touched
        assert not r._replicas[0].in_rotation
        assert r._replicas[1].client.generation == 0
        # the fleet still serves on the untouched replica
        assert r.submit(_prompt(), max_new_tokens=2).result(30).shape == (6,)
    finally:
        r.stop()


# -- drain / lifecycle -------------------------------------------------------

def test_router_drain_is_typed_and_idempotent():
    r = ServingRouter([_factory(FakeModel(delay_s=0.05), max_batch_size=1),
                       _factory(FakeModel(delay_s=0.05), max_batch_size=1)],
                      probe_interval_s=0.05)
    try:
        futs = [r.submit(_prompt(), max_new_tokens=2) for _ in range(6)]
        res = r.drain(timeout=1.0)
        oks, errs = _resolve_all(futs, timeout=10)
        assert len(oks) + len(errs) == 6
        assert all(isinstance(e, EngineDrainingError) for e in errs)
        with pytest.raises(EngineDrainingError):
            r.submit(_prompt(), max_new_tokens=2)
        assert r.drain(timeout=0.5)["shed"] == 0     # idempotent
        assert r.health()["state"] == "draining"
        assert res["wall_s"] >= 0
    finally:
        r.stop()


def test_router_metrics_and_flight_events():
    import paddlepaddle_tpu.observability as obs
    from paddlepaddle_tpu.observability import flight

    obs.reset()     # cold-path counters record even with obs off: earlier
    obs.enable(trace=False, metrics=True, watchdog_=False)  # tests' traffic
    flight.enable(capacity=256)                             # must not leak in
    r = ServingRouter([_factory(max_batch_size=1),
                       _factory(max_batch_size=1)],
                      probe_interval_s=_QUIET, breaker_threshold=2,
                      breaker_reset_s=30.0)
    r.start()
    try:
        r._probe_once()
        for _ in range(4):
            r.submit(_prompt(), max_new_tokens=2).result(30)
        r._replicas[0].client.kill()
        r._probe_once()
        r._probe_once()                       # threshold 2 -> eviction
        assert r._replicas[0].breaker.state == "open"
        snap = obs.snapshot()
        picks = snap.get("paddle_router_picks_total", {})
        assert sum(picks.values()) == 4
        evs = snap.get("paddle_router_evictions_total", {})
        assert sum(evs.values()) == 1
        assert snap["paddle_router_replicas_healthy"][()] == 1
        events = [e for e in flight.get().events()
                  if e.get("kind") == "router"]
        assert any((e.get("data") or {}).get("event") == "eviction"
                   for e in events)
        text = obs.to_prometheus_text()
        assert "paddle_router_picks_total" in text
        assert "paddle_router_replicas_healthy" in text
    finally:
        flight.disable()
        obs.disable()
        obs.reset()
        r.stop()


# -- chaos drill -------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_kill_one_replica_under_mixed_load():
    """Acceptance drill: 3 replicas under a mixed short/long workload; a
    serving.decode fault storm rages and one replica is killed mid-decode.
    Every submitted future resolves (completed or typed-failed — zero
    silently lost), the fleet keeps serving afterwards, and the dead
    replica's breaker opens then re-admits once it is restarted."""
    from paddlepaddle_tpu.resilience import chaos

    r = ServingRouter(
        [_factory(lambda: FakeModel(delay_s=0.01), max_batch_size=2)
         for _ in range(3)],
        probe_interval_s=0.05, breaker_threshold=3, breaker_reset_s=0.3)
    r.start()
    try:
        # mixed workload: half short, half long prompts, submitted while
        # the storm is armed — chaos fires inside whichever replica's
        # decode attempt hits the seam next
        chaos.configure("serving.decode:exc:x4",
                        seed=int(os.environ.get("PADDLE_CHAOS_SEED", "1234")))
        rng = np.random.default_rng(2)
        futs = []
        for i in range(18):
            n = 4 if i % 2 == 0 else int(rng.integers(16, 32))
            futs.append(r.submit(_prompt(n=n, v=i % 7), max_new_tokens=2))
            if i == 8:
                r._replicas[1].client.kill()      # dies mid-flight
        oks, errs = _resolve_all(futs, timeout=60)
        assert len(oks) + len(errs) == 18         # zero lost futures
        for e in errs:
            # meaningful, not lost: typed serving errors, decode/chaos
            # RuntimeErrors, or the dead replica's ConnectionError when
            # the retry budget lands on it before the next probe
            assert isinstance(e, (ServingError, RuntimeError,
                                  ConnectionError)), e
        # the fleet kept serving: the storm + kill cost at most a few
        # requests, not the workload
        assert len(oks) >= 14, f"only {len(oks)}/18 completed"
        # the dead replica was evicted...
        deadline = time.time() + 5
        while time.time() < deadline \
                and r._replicas[1].breaker.state != "open":
            time.sleep(0.05)
        assert r._replicas[1].breaker.state == "open"
        # ...the survivors still serve...
        assert r.submit(_prompt(), max_new_tokens=2).result(30).shape == (6,)
        # ...and recovery re-admits through the half-open probe
        r._replicas[1].client.restart()
        deadline = time.time() + 10
        while time.time() < deadline \
                and r._replicas[1].breaker.state != "closed":
            time.sleep(0.05)
        assert r._replicas[1].breaker.state == "closed"
        assert r.stats["readmissions"] >= 1
        h = r.health()
        assert h["ok"] and h["router"]["healthy"] == 3
        # then a rolling restart across the whole fleet drops nothing
        futs = [r.submit(_prompt(), max_new_tokens=2) for _ in range(6)]
        res = r.rolling_restart(drain_timeout=5.0, health_timeout=10.0)
        assert res["ok"]
        oks, errs = _resolve_all(futs)
        assert len(oks) == 6 and not errs
    finally:
        chaos.disable()
        r.stop()


# -- continuous engines (real model) -----------------------------------------

def test_router_over_continuous_engines():
    """Two real tiny-Llama continuous-batching replicas behind the router:
    results are real generations, prefix-affine requests land on one
    replica's prompt cache, and health exposes the paged-pool headroom."""
    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(
        vocab_size=64, hidden_size=32, layers=2, heads=4, kv_heads=2,
        max_len=128))
    factory = lambda: ServingEngine(  # noqa: E731
        model, max_batch_size=2, decode_chunk=4, kv_page_size=16)
    rng = np.random.default_rng(3)
    with ServingRouter([factory, factory], probe_interval_s=0.1) as r:
        p = rng.integers(0, 64, (8,)).astype(np.int32)
        out = r.submit(p, max_new_tokens=4).result(300)
        assert out.shape == (12,) and (out[:8] == p).all()
        # shared system prompt: all three land on ONE replica's cache
        sysp = rng.integers(0, 64, (18,)).astype(np.int32)
        futs = [r.submit(np.concatenate(
            [sysp, rng.integers(0, 64, (4,)).astype(np.int32)]),
            max_new_tokens=3, prefix_len=18) for _ in range(3)]
        for f in futs:
            assert f.result(300).shape == (25,)
        hits = [rep.client.engine._engine.prefix.hits
                for rep in r._replicas]
        assert sorted(hits) == [0, 2], hits    # 1 miss + 2 hits, one home
        h = r.health()
        assert h["ok"] and h["router"]["completed"] == 4
        assert all(v["pages_free"] is not None
                   for v in h["replicas"].values())


# -- hedged requests ---------------------------------------------------------

def _force_pick(r, idx):
    """Make replica ``idx`` the unambiguous least-wait pick."""
    r._probe_once()
    for i, rep in enumerate(r._replicas):
        rep.snapshot = dict(rep.snapshot or {}, ok=True,
                            est_wait_s=(0.0 if i == idx else 30.0))


def test_hedge_duplicates_to_other_replica_and_wins():
    """Primary stuck pre-first-token past hedge_after_s ⇒ a duplicate on a
    DIFFERENT replica; first terminal wins, delivery is exactly-once."""
    slow, fast = FakeModel(delay_s=1.2), FakeModel()
    r = ServingRouter([_factory(slow, max_batch_size=1),
                       _factory(fast, max_batch_size=1)],
                      probe_interval_s=_QUIET, hedge_after_s=0.15)
    r.start()
    try:
        _force_pick(r, 0)                      # primary = the slow one
        t0 = time.perf_counter()
        fut = r.submit(_prompt(), max_new_tokens=2)
        out = fut.result(30)
        took = time.perf_counter() - t0
        assert out.shape == (6,)
        assert took < 1.0, f"hedge never rescued: {took:.2f}s"
        assert slow.calls + fast.calls >= 2    # the duplicate really ran
        time.sleep(1.3)                        # let the loser terminal land
        assert r.stats["hedges"] == 1
        assert r.stats["hedge_wins"] == 1
        assert r.stats["completed"] == 1       # exactly-once delivery
        assert r.stats["failed"] == 0
    finally:
        r.stop()


def test_hedge_loses_gracefully_when_primary_finishes_first():
    primary, other = FakeModel(delay_s=0.4), FakeModel(delay_s=1.5)
    r = ServingRouter([_factory(primary, max_batch_size=1),
                       _factory(other, max_batch_size=1)],
                      probe_interval_s=_QUIET, hedge_after_s=0.1)
    r.start()
    try:
        _force_pick(r, 0)
        fut = r.submit(_prompt(), max_new_tokens=2)
        assert fut.result(30).shape == (6,)
        time.sleep(1.4)                        # hedge terminal lands late
        assert r.stats["hedges"] == 1
        assert r.stats["hedge_wins"] == 0
        assert r.stats["completed"] == 1
    finally:
        r.stop()


def test_hedge_needs_a_second_replica():
    r = ServingRouter([_factory(FakeModel(delay_s=0.4), max_batch_size=1)],
                      probe_interval_s=_QUIET, hedge_after_s=0.05)
    r.start()
    try:
        fut = r.submit(_prompt(), max_new_tokens=2)
        assert fut.result(30).shape == (6,)
        assert r.stats["hedges"] == 0          # nothing to hedge onto
    finally:
        r.stop()


def test_hedge_budget_caps_duplicate_rate():
    """The budget is a hard fraction of submits: a fleet-wide slowdown
    must not double total load via hedging."""
    import paddlepaddle_tpu.observability as obs

    obs.reset()
    mk = lambda: FakeModel(delay_s=0.5)
    r = ServingRouter([_factory(mk(), max_batch_size=4),
                       _factory(mk(), max_batch_size=4)],
                      probe_interval_s=_QUIET, hedge_after_s=0.05,
                      hedge_budget_pct=10.0)
    r.start()
    try:
        r._probe_once()
        futs = [r.submit(_prompt(), max_new_tokens=2) for _ in range(6)]
        oks, errs = _resolve_all(futs)
        assert len(oks) == 6 and not errs
        # 10% of 6 submits floors at max(1, 0.6) = 1 allowed hedge
        assert r.stats["hedges"] <= 1
        text = obs.to_prometheus_text()
        assert 'paddle_router_hedges_total' in text
        assert 'outcome="suppressed"' in text
    finally:
        r.stop()
        obs.reset()


def test_hedge_auto_is_off_without_ttft_history():
    """hedge_after_s="auto" derives its delay from observed TTFT — with
    no history there is no defensible number, so auto means OFF, never a
    guessed constant."""
    import paddlepaddle_tpu.observability as obs

    obs.reset()
    r = ServingRouter([_factory(), _factory()],
                      probe_interval_s=_QUIET, hedge_after_s="auto")
    r.start()
    try:
        assert r._hedge_delay() is None
        fut = r.submit(_prompt(), max_new_tokens=2)
        assert fut.result(30).shape == (6,)
        assert r.stats["hedges"] == 0
    finally:
        r.stop()
        obs.reset()


def test_hedge_off_values():
    for off in (None, 0, 0.0, "off"):
        r = ServingRouter([_factory(), _factory()],
                          probe_interval_s=_QUIET, hedge_after_s=off)
        try:
            assert r._hedge_delay() is None, f"hedge_after_s={off!r}"
        finally:
            r.stop()


class _GrayAcceptClient(ReplicaClient):
    """A replica whose submit() call itself wedges — the remote client's
    blocking accept round trip under a delayed/black-holed accepted frame
    (it blocks the dispatcher until the stall watchdog fires). The hedge
    must cover this window too, not just the post-accept stream."""

    def __init__(self, factory, name, block_s):
        super().__init__(factory, name=name)
        self.block_s = block_s

    def submit(self, prompt_ids, **kw):
        time.sleep(self.block_s)
        return super().submit(prompt_ids, **kw)


def test_hedge_covers_gray_accept_blocked_in_submit():
    """The dispatcher blocked inside client.submit (gray accept) is the
    nastiest pre-first-token tail: pend.inner is still None when the
    hedge timer fires, and the hedge must dispatch anyway — to a
    DIFFERENT replica — and win while the primary is still wedged."""
    gray = _GrayAcceptClient(_factory(FakeModel(), max_batch_size=1),
                             "r0", block_s=1.5)
    fast = FakeModel()
    r = ServingRouter([gray, _factory(fast, max_batch_size=1)],
                      probe_interval_s=_QUIET, hedge_after_s=0.15)
    r.start()
    try:
        _force_pick(r, 0)
        fut = r.submit(_prompt(), max_new_tokens=2)
        out = fut.result(30)
        assert out.shape == (6,)
        # the hedge delivered while the primary was still blocked: the
        # future's first token landed well inside the 1.5 s accept wedge
        slo = fut.slo()
        assert slo["ttft_s"] is not None and slo["ttft_s"] < 1.2, slo
        assert fast.calls >= 1                 # the duplicate really ran
        time.sleep(0.3)                        # primary attempt unwinds
        assert r.stats["hedges"] == 1
        assert r.stats["hedge_wins"] == 1
        assert r.stats["completed"] == 1       # exactly-once delivery
        assert r.stats["failed"] == 0
    finally:
        r.stop()
