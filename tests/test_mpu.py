"""TP/mpu layers + fleet topology tests (8 virtual CPU devices)."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.distributed import fleet
from paddlepaddle_tpu.distributed.mesh import ProcessMesh
from paddlepaddle_tpu.nn import functional as F
from paddlepaddle_tpu.parallel import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    ShardedTrainStep,
    VocabParallelEmbedding,
)


def test_fleet_init_topology():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                               "sharding_degree": 2, "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_sharding_parallel_world_size() == 2
    assert hcg.topology().world_size() == 8
    assert hcg.mesh.get_dim_size("mp") == 2


def test_mpu_layers_numerics_match_serial():
    """Column->Row pair == plain two-layer MLP given same weights."""
    paddle.seed(0)
    col = ColumnParallelLinear(8, 16, has_bias=True, gather_output=False)
    row = RowParallelLinear(16, 4, has_bias=True, input_is_parallel=True)
    x = np.random.default_rng(0).standard_normal((2, 8)).astype(np.float32)
    y = row(col(x))
    ref = F.linear(F.linear(paddle.to_tensor(x), col.weight, col.bias), row.weight, row.bias)
    np.testing.assert_allclose(y.numpy(), ref.numpy(), rtol=1e-5)
    assert col.weight.dist_spec == (None, "mp")
    assert row.weight.dist_spec == ("mp", None)


def test_parallel_cross_entropy_matches_dense():
    logits = np.random.default_rng(0).standard_normal((4, 10)).astype(np.float32)
    labels = np.array([1, 3, 5, 7], np.int64)
    pce = ParallelCrossEntropy()
    out = pce(paddle.to_tensor(logits), paddle.to_tensor(labels))
    ref = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels), reduction="none")
    np.testing.assert_allclose(np.squeeze(out.numpy()), np.squeeze(ref.numpy()), rtol=1e-5)


def test_tp_model_sharded_train():
    """An mpu-built MLP trains under ShardedTrainStep with dist_spec placements."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    class TPMlp(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = VocabParallelEmbedding(32, 16)
            self.fc1 = ColumnParallelLinear(16, 32, gather_output=False)
            self.fc2 = RowParallelLinear(32, 32, input_is_parallel=True)

        def forward(self, ids, labels):
            h = self.fc2(self.fc1(self.embed(ids))).mean(axis=1)
            return F.cross_entropy(h, labels)

    mesh = ProcessMesh(shape=[2, 2, 2], dim_names=["dp", "fsdp", "mp"])
    m = TPMlp()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    step = ShardedTrainStep(m, opt, lambda mm, ids, labels: mm(ids, labels),
                            mesh=mesh, rules=[(r".*", ())])
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 32, (8, 4)).astype(np.int32)
    labels = rng.integers(0, 32, (8,)).astype(np.int64)
    losses = [float(step(ids, labels).numpy()) for _ in range(6)]
    assert losses[-1] < losses[0]
    name = next(n for n in step.params if n.endswith("fc1.weight"))
    assert not step.params[name].sharding.is_fully_replicated


def test_rng_state_tracker():
    from paddlepaddle_tpu.distributed.fleet import get_rng_state_tracker, model_parallel_random_seed

    model_parallel_random_seed(1234)
    tracker = get_rng_state_tracker()
    with tracker.rng_state():
        a = paddle.rand([4])
    with tracker.rng_state():
        b = paddle.rand([4])
    c = paddle.rand([4])
    assert not np.allclose(a.numpy(), c.numpy())
    assert not np.allclose(a.numpy(), b.numpy())
