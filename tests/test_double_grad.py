"""Higher-order eager autograd: paddle.grad(create_graph=True).

Reference surface: python/paddle/base/dygraph/base.py:656 (create_graph) and
the generated double-grad chains in paddle/phi/ops/yaml/backward.yaml. Here
the backward pass itself is recorded on the tape (vjp-of-vjp via the
dispatcher), so gradients compose to arbitrary order with zero per-op
backward code; checked against closed forms and numeric second derivatives.
"""

import numpy as np

import paddlepaddle_tpu as paddle


def test_double_grad_polynomial():
    xv = np.array([1.5, -2.0, 3.0], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    y = (x ** 3).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g1.numpy(), 3 * xv ** 2, rtol=1e-6)
    assert not g1.stop_gradient  # the gradient carries its own graph
    (g2,) = paddle.grad(g1.sum(), x)
    np.testing.assert_allclose(g2.numpy(), 6 * xv, rtol=1e-6)


def test_double_grad_exp_mul():
    xv = np.array([0.3, -0.7], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    y = paddle.exp(2 * x).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x)
    np.testing.assert_allclose(g2.numpy(), 4 * np.exp(2 * xv), rtol=1e-5)


def test_double_grad_matmul_vs_numeric():
    """Mixed second derivative d/dw of (dL/dx).sum for L=(x@w)^2, vs
    central-difference numeric (the OpTest-style check)."""
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((3, 4)).astype(np.float32)
    wv = rng.standard_normal((4, 2)).astype(np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    w = paddle.to_tensor(wv, stop_gradient=False)
    z = (paddle.matmul(x, w) ** 2).sum()
    (gx,) = paddle.grad(z, x, create_graph=True)
    (gw2,) = paddle.grad(gx.sum(), w)

    eps = 1e-3
    num = np.zeros_like(wv)
    for i in range(wv.shape[0]):
        for j in range(wv.shape[1]):
            wp, wm = wv.copy(), wv.copy()
            wp[i, j] += eps
            wm[i, j] -= eps
            gxsum = lambda wc: (2 * (xv @ wc) @ wc.T).sum()
            num[i, j] = (gxsum(wp) - gxsum(wm)) / (2 * eps)
    np.testing.assert_allclose(gw2.numpy(), num, rtol=1e-3, atol=1e-3)


def test_third_order():
    x = paddle.to_tensor(np.array([1.2], np.float32), stop_gradient=False)
    y = (x ** 4).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x, create_graph=True)
    (g3,) = paddle.grad(g2.sum(), x)
    np.testing.assert_allclose(g3.numpy(), [24 * 1.2], rtol=1e-5)


def test_grad_wrt_grad_outputs():
    """A grad_outputs tensor with requires-grad participates in the taped
    backward: d(x^2 backward with seed v)/dv = 2x."""
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    v = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = x ** 2
    (g1,) = paddle.grad(y, x, grad_outputs=v, create_graph=True)
    (gv,) = paddle.grad(g1, v)
    np.testing.assert_allclose(gv.numpy(), [4.0], rtol=1e-6)


def test_gradient_penalty_training_step():
    """The canonical create_graph use: a WGAN-GP-style gradient-norm penalty
    optimized with a standard optimizer."""
    rng = np.random.default_rng(1)
    lin = paddle.nn.Linear(4, 1)
    lin.weight.set_value(np.full((4, 1), 1.0, np.float32))  # start far from
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=lin.parameters())
    xv = rng.standard_normal((8, 4)).astype(np.float32)
    losses = []
    for _ in range(20):
        x = paddle.to_tensor(xv, stop_gradient=False)
        out = lin(x).sum()
        (gx,) = paddle.grad(out, x, create_graph=True)
        penalty = ((gx ** 2).sum() - 1.0) ** 2
        penalty.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(penalty.numpy()))
    assert losses[-1] < losses[0]  # the penalty is actually trainable


def test_first_order_paths_unchanged():
    """create_graph=False still detaches (grads carry no graph)."""
    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = (x ** 2).sum()
    (g,) = paddle.grad(y, x)
    assert g.stop_gradient
    np.testing.assert_allclose(g.numpy(), [6.0])
