"""Fault-tolerant runtime: chaos injection, retry/backoff, preemption
handling, checkpoint integrity (resilience/)."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.distributed import checkpoint as dist_ckpt
from paddlepaddle_tpu.observability import get_registry
from paddlepaddle_tpu.resilience import chaos
from paddlepaddle_tpu.resilience.chaos import ChaosError, chaos_point
from paddlepaddle_tpu.resilience.integrity import (
    CheckpointCorruptionError,
    CheckpointManager,
    find_latest_valid_checkpoint,
    validate_checkpoint,
)
from paddlepaddle_tpu.resilience.retry import (
    RetryPolicy,
    call_with_retry,
    compute_delay,
    retry,
)

REPO = str(Path(__file__).resolve().parent.parent)

# the whole module is part of the chaos suite (tools/run_chaos.sh); it stays
# in tier-1 too — these are fast, in-process unit tests
pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _chaos_off():
    """Every test starts and ends with chaos disarmed (module-global)."""
    chaos.disable()
    yield
    chaos.disable()


def _counter_value(name, **labels):
    m = get_registry().get(name)
    return m.value(**labels) if m is not None else 0


# -- chaos engine ------------------------------------------------------------

def test_chaos_spec_parsing():
    specs = chaos.parse_specs(
        "store.get:exc:0.25; ckpt.write_shard:latency:@3:0.2,"
        "step:kill:%4:7")
    assert [(s.point, s.mode, s.sched_kind, s.sched_value) for s in specs] == [
        ("store.get", "exc", "prob", 0.25),
        ("ckpt.write_shard", "latency", "at", 3.0),
        ("step", "kill", "every", 4.0),
    ]
    assert specs[1].arg == 0.2 and specs[2].arg == 7.0
    with pytest.raises(ValueError, match="needs name:mode:sched"):
        chaos.parse_specs("store.get:exc")
    with pytest.raises(ValueError, match="not in exc"):
        chaos.parse_specs("store.get:boom:0.5")


def test_chaos_exact_hit_schedule():
    chaos.configure("p:exc:@3")
    chaos_point("p")
    chaos_point("p")
    with pytest.raises(ChaosError, match="chaos injected at 'p'"):
        chaos_point("p")
    chaos_point("p")  # only the 3rd hit fires
    assert chaos.fire_counts() == {"p": 1}
    assert chaos.hit_counts()["p"] == 4


def test_chaos_first_n_and_every_n_schedules():
    chaos.configure("a:exc:x2; b:exc:%3")
    fired = []
    for point in ("a", "a", "a", "b", "b", "b", "b", "b", "b"):
        try:
            chaos_point(point)
            fired.append(0)
        except ChaosError:
            fired.append(1)
    #     a  a  a  b  b  b  b  b  b
    assert fired == [1, 1, 0, 0, 0, 1, 0, 0, 1]


def test_chaos_probability_is_seed_deterministic():
    def decisions(seed):
        chaos.configure("p:exc:0.5", seed=seed)
        out = []
        for _ in range(40):
            try:
                chaos_point("p")
                out.append(0)
            except ChaosError:
                out.append(1)
        return out

    a, b = decisions(1234), decisions(1234)
    assert a == b  # reproducible
    assert 0 < sum(a) < 40  # actually probabilistic
    assert decisions(99) != a  # and seed-sensitive


def test_chaos_latency_mode_sleeps():
    chaos.configure("p:latency:x1:0.15")
    t0 = time.perf_counter()
    chaos_point("p")
    assert time.perf_counter() - t0 >= 0.14


def test_chaos_disabled_is_noop():
    chaos.disable()
    for _ in range(3):
        chaos_point("anything")  # no engine, no error, no state


def test_chaos_injection_metrics():
    chaos.configure("p:exc:x1")
    before = _counter_value("paddle_chaos_injections_total",
                            point="p", mode="exc")
    with pytest.raises(ChaosError):
        chaos_point("p")
    assert _counter_value("paddle_chaos_injections_total",
                          point="p", mode="exc") == before + 1


# -- retry/backoff -----------------------------------------------------------

def test_retry_backoff_timing_and_success():
    delays = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise ConnectionError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0,
                         max_delay=10.0, jitter=0.0)
    out = call_with_retry(flaky, policy=policy, sleep=delays.append)
    assert out == "ok" and len(calls) == 4
    # exponential: 0.1, 0.2, 0.4 (no jitter)
    np.testing.assert_allclose(delays, [0.1, 0.2, 0.4])


def test_retry_jitter_bounded_and_capped():
    import random

    policy = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.25,
                         jitter=0.5)
    rng = random.Random(0)
    for attempt, base in [(1, 0.1), (2, 0.2), (3, 0.25), (9, 0.25)]:
        for _ in range(20):
            d = compute_delay(policy, attempt, rng)
            assert base <= d <= base * 1.5


def test_retry_exhaustion_raises_last_error():
    def always_fails():
        raise TimeoutError("still down")

    with pytest.raises(TimeoutError, match="still down"):
        call_with_retry(always_fails,
                        policy=RetryPolicy(max_attempts=3, base_delay=0.001))


def test_retry_non_retryable_propagates_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        call_with_retry(bad, policy=RetryPolicy(max_attempts=5,
                                                base_delay=0.001))
    assert len(calls) == 1  # no retry on non-transient errors


def test_retry_deadline_stops_early():
    delays = []
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("down")

    policy = RetryPolicy(max_attempts=100, base_delay=10.0, jitter=0.0,
                         deadline=1.0)
    with pytest.raises(ConnectionError):
        call_with_retry(flaky, policy=policy, sleep=delays.append)
    assert len(calls) == 1 and delays == []  # first backoff would bust it


def test_retry_decorator_and_metrics():
    calls = []

    @retry(RetryPolicy(max_attempts=3, base_delay=0.001), name="unit.flaky")
    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise OSError("blip")
        return 42

    before = _counter_value("paddle_retry_attempts_total", op="unit.flaky")
    assert flaky() == 42
    assert _counter_value("paddle_retry_attempts_total",
                          op="unit.flaky") == before + 1


def test_chaos_error_is_retryable_by_default():
    chaos.configure("p:exc:x2")

    def op():
        chaos_point("p")
        return "recovered"

    assert call_with_retry(
        op, policy=RetryPolicy(max_attempts=3, base_delay=0.001)) == "recovered"


# -- store seams -------------------------------------------------------------

def test_store_get_retries_injected_faults():
    from paddlepaddle_tpu.distributed.store import TCPStore

    s = TCPStore(is_master=True)
    s.set("k", b"v")
    chaos.configure("store.get:exc:x2")  # first two attempts fail
    before = _counter_value("paddle_retry_attempts_total", op="store.get")
    assert s.get("k") == b"v"  # retry absorbs both injected faults
    assert chaos.fire_counts()["store.get"] == 2
    assert _counter_value("paddle_retry_attempts_total",
                          op="store.get") == before + 2


def test_store_get_exhausts_on_persistent_fault():
    from paddlepaddle_tpu.distributed.store import TCPStore

    s = TCPStore(is_master=True)
    s.set("k", b"v")
    chaos.configure("store.get:exc:1.0")  # every attempt fails
    with pytest.raises(ChaosError):
        s.get("k")


# -- checkpoint integrity (format v3) ---------------------------------------

def _state(n=4):
    m = paddle.nn.Linear(n, n)
    return m, m.state_dict()


def test_v3_metadata_records_crc(tmp_path):
    _, sd = _state()
    ck = str(tmp_path / "ckpt")
    dist_ckpt.save_state_dict(sd, ck)
    meta = dist_ckpt.get_checkpoint_metadata(ck)
    assert meta["format"].endswith("v3")
    for rec in meta["tensors"].values():
        for s in rec["shards"]:
            assert isinstance(s["crc32"], int)
    validate_checkpoint(ck)  # full CRC pass succeeds


def _flip_byte(fpath, offset=-3):
    with open(fpath, "r+b") as f:
        f.seek(offset, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))


def test_bitflip_detected_on_load(tmp_path):
    m, sd = _state()
    ck = str(tmp_path / "ckpt")
    dist_ckpt.save_state_dict(sd, ck)
    meta = dist_ckpt.get_checkpoint_metadata(ck)
    victim = meta["tensors"]["weight"]["shards"][0]["file"]
    _flip_byte(os.path.join(ck, victim))

    with pytest.raises(CheckpointCorruptionError, match="CRC mismatch"):
        validate_checkpoint(ck)
    m2 = paddle.nn.Linear(4, 4)
    with pytest.raises(CheckpointCorruptionError, match="CRC mismatch"):
        dist_ckpt.load_state_dict(m2.state_dict(), ck)


def test_crc_verify_flag_opt_out(tmp_path):
    m, sd = _state()
    ck = str(tmp_path / "ckpt")
    dist_ckpt.save_state_dict(sd, ck)
    meta = dist_ckpt.get_checkpoint_metadata(ck)
    _flip_byte(os.path.join(ck, meta["tensors"]["weight"]["shards"][0]["file"]))
    paddle.set_flags({"FLAGS_ckpt_verify_crc": False})
    try:
        m2 = paddle.nn.Linear(4, 4)
        dist_ckpt.load_state_dict(m2.state_dict(), ck)  # no CRC gate: loads
    finally:
        paddle.set_flags({"FLAGS_ckpt_verify_crc": True})


def test_uncommitted_dir_is_invalid(tmp_path):
    d = tmp_path / "torn"
    d.mkdir()
    (d / "weight.npy").write_bytes(b"partial")
    with pytest.raises(CheckpointCorruptionError, match="no metadata.json"):
        validate_checkpoint(str(d))


def test_atomic_commit_overwrite_never_torn(tmp_path):
    """Saving twice to one path goes through staging+rename; the final dir
    is always one complete checkpoint (old or new, never a mix)."""
    ck = str(tmp_path / "ckpt")
    m1, sd1 = _state()
    dist_ckpt.save_state_dict(sd1, ck)
    w1 = sd1["weight"].numpy().copy()
    m2 = paddle.nn.Linear(4, 4)
    dist_ckpt.save_state_dict(m2.state_dict(), ck)  # overwrite
    validate_checkpoint(ck)
    m3 = paddle.nn.Linear(4, 4)
    sd3 = m3.state_dict()
    dist_ckpt.load_state_dict(sd3, ck)
    assert not np.allclose(sd3["weight"].numpy(), w1)  # it's the NEW one
    # no staging or trash litter after successful commits
    leftovers = [n for n in os.listdir(tmp_path)
                 if "staging" in n or "__old__" in n]
    assert leftovers == []


def test_kill_during_save_leaves_no_torn_checkpoint(tmp_path):
    """Chaos kill inside the shard write: the process dies mid-save; the
    target path must be absent entirely (atomic commit) and no uncommitted
    directory may contain a metadata.json."""
    ck = str(tmp_path / "ckpt")
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import paddlepaddle_tpu as paddle\n"
        "from paddlepaddle_tpu.distributed import checkpoint as dist_ckpt\n"
        "m = paddle.nn.Linear(8, 8)\n"
        f"dist_ckpt.save_state_dict(m.state_dict(), {ck!r})\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_CHAOS_POINTS="ckpt.write_shard:kill:@1:77",
               PADDLE_CHAOS_SEED="1234")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode == 77, (proc.returncode, proc.stderr[-2000:])
    assert not os.path.exists(ck)  # committed-or-absent
    for root, _dirs, files in os.walk(tmp_path):
        assert "metadata.json" not in files, f"torn metadata in {root}"
    with pytest.raises(CheckpointCorruptionError):
        validate_checkpoint(ck)


# -- CheckpointManager: keep-K GC + newest-valid fallback --------------------

def test_manager_keeps_last_k_and_restores_newest(tmp_path):
    root = str(tmp_path / "run")
    mgr = CheckpointManager(root, keep_last_k=3)
    m, sd = _state()
    saved = {}
    for step in range(1, 6):
        sd["weight"].set_value(np.full((4, 4), float(step), np.float32))
        mgr.save(sd, step)
        saved[step] = sd["weight"].numpy().copy()
    from paddlepaddle_tpu.resilience.integrity import list_checkpoints

    assert [s for s, _ in list_checkpoints(root)] == [5, 4, 3]  # GC'd to K=3
    m2 = paddle.nn.Linear(4, 4)
    sd2 = m2.state_dict()
    assert mgr.restore(sd2) == 5
    np.testing.assert_allclose(sd2["weight"].numpy(), saved[5])


def test_manager_falls_back_past_corrupt_newest(tmp_path):
    root = str(tmp_path / "run")
    mgr = CheckpointManager(root, keep_last_k=3)
    m, sd = _state()
    saved = {}
    for step in range(1, 4):
        sd["weight"].set_value(np.full((4, 4), float(step), np.float32))
        mgr.save(sd, step)
        saved[step] = sd["weight"].numpy().copy()
    # corrupt the newest checkpoint's first shard
    meta = dist_ckpt.get_checkpoint_metadata(mgr.step_path(3))
    _flip_byte(os.path.join(mgr.step_path(3),
                            meta["tensors"]["weight"]["shards"][0]["file"]))
    before = _counter_value("paddle_ckpt_fallbacks_total")
    assert find_latest_valid_checkpoint(root)[0] == 2
    m2 = paddle.nn.Linear(4, 4)
    sd2 = m2.state_dict()
    assert mgr.restore(sd2) == 2  # skipped the corrupt step-3
    np.testing.assert_allclose(sd2["weight"].numpy(), saved[2])
    assert _counter_value("paddle_ckpt_fallbacks_total") > before


def test_manager_recovers_old_dir_from_interrupted_overwrite(tmp_path):
    """A kill between the commit's two renames leaves the previous good
    checkpoint at <step>.__old__.<pid>: restore must still find it, and the
    next successful commit's GC must clean it up."""
    root = str(tmp_path / "run")
    mgr = CheckpointManager(root, keep_last_k=3)
    m, sd = _state()
    sd["weight"].set_value(np.full((4, 4), 3.0, np.float32))
    mgr.save(sd, 3)
    # simulate the crash window: canonical renamed aside, new one never landed
    os.rename(mgr.step_path(3), mgr.step_path(3) + ".__old__.999")
    assert find_latest_valid_checkpoint(root)[0] == 3
    m2 = paddle.nn.Linear(4, 4)
    sd2 = m2.state_dict()
    assert mgr.restore(sd2) == 3  # recovered from the __old__ dir
    np.testing.assert_allclose(sd2["weight"].numpy(), 3.0)
    # a completed re-save supersedes the leftover; GC removes it
    mgr.save(sd, 3)
    assert not os.path.exists(mgr.step_path(3) + ".__old__.999")
    assert os.path.exists(mgr.step_path(3))


def test_preemption_reinstall_keeps_cooperative_mode():
    """Adding a callback with default args must not flip a polling-mode
    handler back into exit-on-signal mode."""
    from paddlepaddle_tpu.resilience import (
        install_preemption_handler,
        uninstall_preemption_handler,
    )

    try:
        h = install_preemption_handler(exit_on_signal=False, exit_code=7)
        h2 = install_preemption_handler(lambda: None)  # defaults: no override
        assert h2 is h
        assert h.exit_on_signal is False and h.exit_code == 7
        h3 = install_preemption_handler(exit_code=31)  # explicit: overrides
        assert h3.exit_code == 31 and h3.exit_on_signal is False
    finally:
        uninstall_preemption_handler()


def test_manager_restore_empty_root(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "none"), keep_last_k=2)
    m, sd = _state()
    assert mgr.restore(sd) is None
    assert mgr.latest_valid() is None


# -- wait_all_saves: every failure surfaced, state never poisoned ------------

def test_wait_all_saves_aggregates_all_failures(tmp_path):
    m, sd = _state()
    # Linear has 2 tensors -> 2 shard files per save; 3 retry attempts per
    # file; x6 fails the first file of BOTH async saves through its retries
    chaos.configure("ckpt.write_shard:exc:x6")
    dist_ckpt.save_state_dict(sd, str(tmp_path / "a"), async_save=True)
    dist_ckpt.save_state_dict(sd, str(tmp_path / "b"), async_save=True)
    with pytest.raises(dist_ckpt.CheckpointSaveError,
                       match="2 async checkpoint saves failed") as ei:
        dist_ckpt.wait_all_saves()
    assert len(ei.value.errors) == 2
    assert all(isinstance(e, ChaosError) for e in ei.value.errors)
    # pending list cleared: the NEXT save/wait is not poisoned
    dist_ckpt.wait_all_saves()
    chaos.disable()
    dist_ckpt.save_state_dict(sd, str(tmp_path / "c"), async_save=True)
    dist_ckpt.wait_all_saves()
    validate_checkpoint(str(tmp_path / "c"))


def test_single_async_failure_reraised_as_is(tmp_path):
    m, sd = _state()
    chaos.configure("ckpt.write_shard:exc:x3")  # one save, all 3 attempts
    dist_ckpt.save_state_dict(sd, str(tmp_path / "a"), async_save=True)
    with pytest.raises(ChaosError):
        dist_ckpt.wait_all_saves()


# -- preemption --------------------------------------------------------------

def test_preemption_cooperative_flag_and_callbacks():
    from paddlepaddle_tpu.resilience import (
        install_preemption_handler,
        preemption_requested,
        uninstall_preemption_handler,
    )

    ran = []
    try:
        h = install_preemption_handler(lambda: ran.append("saved"),
                                       exit_on_signal=False)
        assert not preemption_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5
        while time.time() < deadline and not preemption_requested():
            time.sleep(0.01)
        assert preemption_requested()
        assert ran == ["saved"]
        assert h.requested()
    finally:
        uninstall_preemption_handler()


def test_preemption_sigterm_saves_and_exits_restartable(tmp_path):
    """SIGTERM → emergency save_state_dict + drain → exit 143: the full
    preemption flow in a real process."""
    ck = str(tmp_path / "emergency")
    code = (
        "import os, sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import paddlepaddle_tpu as paddle\n"
        "from paddlepaddle_tpu.distributed import checkpoint as dist_ckpt\n"
        "from paddlepaddle_tpu.resilience import install_preemption_handler\n"
        "m = paddle.nn.Linear(8, 8)\n"
        "install_preemption_handler(\n"
        f"    lambda: dist_ckpt.save_state_dict(m.state_dict(), {ck!r}))\n"
        "print('READY', flush=True)\n"
        "time.sleep(120)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        proc.kill()
    assert rc == 143, (rc, proc.stderr.read()[-2000:])
    validate_checkpoint(ck)  # the emergency checkpoint is complete + intact
    m2 = paddle.nn.Linear(8, 8)
    dist_ckpt.load_state_dict(m2.state_dict(), ck)


# -- watchdog re-arm (satellite) ---------------------------------------------

def test_watchdog_rearms_after_timed_out_step_retires():
    from paddlepaddle_tpu.distributed.watchdog import Watchdog

    fired = []
    before = _counter_value("paddle_watchdog_step_timeouts_total",
                            step="slow")
    wd = Watchdog(timeout=0.2, poll_interval=0.05, abort=False,
                  on_timeout=lambda name, el: fired.append(name))
    with wd:
        with wd.step("slow"):
            time.sleep(0.5)
        with wd.step("fast"):
            time.sleep(0.01)
        with wd.step("slow"):
            time.sleep(0.5)  # the one-shot latch used to go dead here
    assert fired == ["slow", "slow"]
    assert _counter_value("paddle_watchdog_step_timeouts_total",
                          step="slow") == before + 2


def test_watchdog_fires_once_per_hung_step():
    from paddlepaddle_tpu.distributed.watchdog import Watchdog

    fired = []
    wd = Watchdog(timeout=0.1, poll_interval=0.02, abort=False,
                  on_timeout=lambda name, el: fired.append(name))
    with wd:
        with wd.step("hung"):
            time.sleep(0.6)  # several poll intervals past the deadline
    assert fired == ["hung"]  # no repeat-fire storm for ONE hung step


def test_step_chaos_seam():
    from paddlepaddle_tpu.distributed.watchdog import Watchdog

    chaos.configure("step:exc:@1")
    wd = Watchdog(timeout=30, abort=False)
    with wd:
        with pytest.raises(ChaosError):
            with wd.step("s"):
                pass


# -- dataloader worker death (satellite) -------------------------------------

def test_chaos_killed_worker_raises_dataloader_worker_error(monkeypatch):
    """A chaos-killed worker (fork start method: children inherit the armed
    engine) surfaces as DataLoaderWorkerError, not a hang."""
    from paddlepaddle_tpu.io import DataLoader, DataLoaderWorkerError
    from paddlepaddle_tpu.io.dataset import Dataset

    class Ds(Dataset):
        def __getitem__(self, i):
            return np.array([i], np.int64)

        def __len__(self):
            return 32

    monkeypatch.setenv("PADDLE_TPU_MP_START_METHOD", "fork")
    chaos.configure("dataloader.worker:kill:@3:99")
    dl = DataLoader(Ds(), batch_size=2, num_workers=2)
    with pytest.raises(DataLoaderWorkerError, match="died unexpectedly"):
        list(dl)


def test_worker_exception_is_dataloader_worker_error():
    from paddlepaddle_tpu.io import DataLoader, DataLoaderWorkerError
    from paddlepaddle_tpu.io.dataset import Dataset

    class Boom(Dataset):
        def __getitem__(self, i):
            raise RuntimeError("boom")

        def __len__(self):
            return 4

    dl = DataLoader(Boom(), batch_size=2, num_workers=0)
    with pytest.raises(RuntimeError):
        list(dl)
    # the mp path's public type: subclass of RuntimeError, importable
    assert issubclass(DataLoaderWorkerError, RuntimeError)
