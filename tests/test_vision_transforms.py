"""Round-4 vision.transforms closure: the full reference __all__ resolves
and the new functional ops match independent oracles (PIL for geometry —
the reference's own backend — and formula oracles for photometry)."""

import ast
import random

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.vision import transforms as T
from paddlepaddle_tpu.vision.transforms import functional as F

rng = np.random.default_rng(4)
IMG = rng.integers(0, 255, (12, 10, 3)).astype(np.uint8)


def test_transforms_namespace_complete():
    import os

    if not os.path.exists("/root/reference"):
        pytest.skip("reference tree not present")
    tree = ast.parse(open(
        "/root/reference/python/paddle/vision/transforms/__init__.py").read())
    names = next(
        [ast.literal_eval(e) for e in n.value.elts]
        for n in ast.walk(tree)
        if isinstance(n, ast.Assign)
        and getattr(n.targets[0], "id", "") == "__all__")
    missing = [n for n in names if not hasattr(T, n)]
    assert not missing, missing


def test_flips_crops_pad():
    np.testing.assert_array_equal(F.hflip(IMG), IMG[:, ::-1])
    np.testing.assert_array_equal(F.vflip(IMG), IMG[::-1])
    np.testing.assert_array_equal(F.crop(IMG, 2, 3, 4, 5),
                                  IMG[2:6, 3:8])
    np.testing.assert_array_equal(F.center_crop(IMG, 6),
                                  IMG[3:9, 2:8])
    p = F.pad(IMG, (1, 2, 3, 4), fill=7)
    assert p.shape == (12 + 2 + 4, 10 + 1 + 3, 3)
    assert (p[0] == 7).all() and (p[:, 0] == 7).all()
    np.testing.assert_array_equal(p[2:14, 1:11], IMG)
    e = F.pad(IMG, 2, padding_mode="reflect")
    np.testing.assert_array_equal(e[2:14, 2:12], IMG)
    np.testing.assert_array_equal(e[1], e[3])        # reflect symmetry
    # per-channel tuple fill (reference: R, G, B)
    rgb = F.pad(IMG, 1, fill=(9, 8, 7))
    assert rgb[0, 0].tolist() == [9, 8, 7]


def test_photometric_oracles():
    f = IMG.astype(np.float32)
    np.testing.assert_array_equal(
        F.adjust_brightness(IMG, 0.5),
        np.clip(np.round(f * 0.5), 0, 255).astype(np.uint8))
    gray = 0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2]
    np.testing.assert_array_equal(
        F.to_grayscale(IMG)[:, :, 0],
        np.clip(np.round(gray), 0, 255).astype(np.uint8))
    mean = round(float(np.round(gray).mean()))
    want = np.clip(np.round(0.3 * f + 0.7 * mean), 0, 255).astype(np.uint8)
    np.testing.assert_allclose(F.adjust_contrast(IMG, 0.3).astype(int),
                               want.astype(int), atol=1)
    sat = np.clip(np.round(0.4 * f + 0.6 * np.round(gray)[..., None]),
                  0, 255).astype(np.uint8)
    np.testing.assert_allclose(F.adjust_saturation(IMG, 0.4).astype(int),
                               sat.astype(int), atol=1)
    # hue: 0 is identity; +1/3 turns pure red into pure green
    np.testing.assert_allclose(F.adjust_hue(IMG, 0.0).astype(int),
                               IMG.astype(int), atol=1)
    red = np.zeros((2, 2, 3), np.uint8)
    red[..., 0] = 255
    g = F.adjust_hue(red, 1.0 / 3)
    assert (g[..., 1] == 255).all() and (g[..., 0] == 0).all()
    with pytest.raises(ValueError):
        F.adjust_hue(IMG, 0.7)
    # grayscale images pass through hue unchanged (reference PIL backend)
    gray2d = IMG[..., 0]
    np.testing.assert_array_equal(F.adjust_hue(gray2d, 0.2), gray2d)


def test_geometry_matches_pil():
    from PIL import Image

    img = rng.integers(0, 255, (16, 16)).astype(np.uint8)
    for angle in (33, -57, 90):
        ours = F.rotate(img, angle, fill=0)
        ref = np.asarray(Image.fromarray(img).rotate(
            angle, resample=Image.NEAREST, fillcolor=0))
        assert (ours != ref).mean() < 0.02, angle
    # expand grows the canvas to hold the rotation
    ex = F.rotate(img, 45, expand=True)
    assert ex.shape[0] > 16 and ex.shape[1] > 16
    ref = np.asarray(Image.fromarray(img).rotate(
        45, resample=Image.NEAREST, expand=True))
    assert abs(ex.shape[0] - ref.shape[0]) <= 1

    # affine identity and integer translation
    np.testing.assert_array_equal(
        F.affine(img, 0, (0, 0), 1.0, (0, 0)), img)
    t = F.affine(img, 0, (2, 3), 1.0, (0, 0), fill=0)
    np.testing.assert_array_equal(t[3:, 2:], img[:-3, :-2])
    assert (t[:3] == 0).all() and (t[:, :2] == 0).all()

    # perspective: identity points -> identity; PIL cross-check
    pts = [(0, 0), (15, 0), (15, 15), (0, 15)]
    np.testing.assert_array_equal(F.perspective(img, pts, pts), img)


def test_erase_and_tensor_paths():
    chw = rng.standard_normal((3, 8, 8)).astype(np.float32)
    out = F.erase(chw.copy(), 2, 3, 4, 2, 9.0)
    assert (out[:, 2:6, 3:5] == 9.0).all()
    assert (out[:, :2] == chw[:, :2]).all()
    t = paddle.to_tensor(chw)
    to = F.erase(t, 1, 1, 2, 2, 0.0)
    assert (to.numpy()[:, 1:3, 1:3] == 0).all()
    tt = F.to_tensor(IMG)
    assert tt.shape == [3, 12, 10]
    np.testing.assert_allclose(tt.numpy(),
                               IMG.transpose(2, 0, 1) / 255.0, rtol=1e-6)


def test_random_transform_classes():
    random.seed(0)
    rrc = T.RandomResizedCrop(8)(IMG)
    assert rrc.shape == (8, 8, 3)
    assert T.RandomVerticalFlip(prob=1.0)(IMG).tolist() == \
        IMG[::-1].tolist()
    assert T.Grayscale(3)(IMG).shape == (12, 10, 3)
    assert T.Pad(2)(IMG).shape == (16, 14, 3)
    jit = T.ColorJitter(0.4, 0.4, 0.4, 0.1)(IMG)
    assert jit.shape == IMG.shape
    rot = T.RandomRotation(30)(IMG)
    assert rot.shape == IMG.shape
    aff = T.RandomAffine(10, translate=(0.1, 0.1), scale=(0.8, 1.2),
                         shear=5)(IMG)
    assert aff.shape == IMG.shape
    per = T.RandomPerspective(prob=1.0)(IMG)
    assert per.shape == IMG.shape
    random.seed(1)
    chw = np.ones((3, 16, 16), np.float32)
    er = T.RandomErasing(prob=1.0)(chw)
    assert (er == 0).any() and er.shape == chw.shape
    # per-channel value and 'random' per-pixel noise (reference contract)
    random.seed(2)
    erc = T.RandomErasing(prob=1.0, value=[5.0, 6.0, 7.0])(chw)
    region = erc != chw
    assert region.any() and (erc[0][region[0]] == 5.0).all()
    random.seed(3)
    ern = T.RandomErasing(prob=1.0, value="random")(chw)
    patch = ern[ern != chw]
    assert patch.size > 1 and np.unique(patch).size > 1   # noise, not const
    # tuple-range jitter parameters accepted (reference _check_input)
    assert T.ColorJitter(brightness=(0.9, 1.1),
                         hue=(-0.1, 0.1))(IMG).shape == IMG.shape
    with pytest.raises(ValueError):
        T.HueTransform(0.7)
    # Compose chains the new classes end to end
    pipe = T.Compose([T.RandomResizedCrop(8), T.ColorJitter(0.2, 0.2),
                      T.ToTensor()])
    assert tuple(pipe(IMG).shape) == (3, 8, 8)


# ---- round-4 vision.datasets closure ---------------------------------------


def _write_png(path, arr):
    from PIL import Image

    Image.fromarray(arr).save(path)


def test_dataset_and_image_folder(tmp_path):
    from paddlepaddle_tpu.vision.datasets import DatasetFolder, ImageFolder

    for ci, cls in enumerate(["ants", "bees"]):
        d = tmp_path / "root" / cls
        d.mkdir(parents=True)
        for k in range(2):
            _write_png(str(d / f"{k}.png"),
                       np.full((4, 4, 3), 40 * ci + k, np.uint8))
    ds = DatasetFolder(str(tmp_path / "root"))
    assert ds.classes == ["ants", "bees"]
    assert len(ds) == 4
    img, label = ds[3]
    assert label == 1 and img[0, 0, 0] == 41
    tds = DatasetFolder(str(tmp_path / "root"),
                        transform=lambda x: x.astype(np.float32) / 255)
    assert tds[0][0].dtype == np.float32

    flat = ImageFolder(str(tmp_path / "root"))
    assert len(flat) == 4 and flat[0][0].shape == (4, 4, 3)
    with pytest.raises(RuntimeError, match="Found 0"):
        ImageFolder(str(tmp_path), extensions=(".xyz",))


def test_fashion_mnist_and_cifar100(tmp_path):
    import pickle
    import struct

    from paddlepaddle_tpu.vision.datasets import Cifar100, FashionMNIST

    imgs = np.arange(2 * 28 * 28, dtype=np.uint8).reshape(2, 28, 28)
    with open(tmp_path / "imgs", "wb") as f:
        f.write(struct.pack(">IIII", 2051, 2, 28, 28) + imgs.tobytes())
    with open(tmp_path / "lbls", "wb") as f:
        f.write(struct.pack(">II", 2049, 2) + bytes([3, 7]))
    ds = FashionMNIST(image_path=str(tmp_path / "imgs"),
                      label_path=str(tmp_path / "lbls"))
    assert len(ds) == 2 and ds[1][1] == 7
    np.testing.assert_array_equal(ds[0][0], imgs[0])

    data = np.arange(3 * 3072, dtype=np.uint8).reshape(3, 3072)
    with open(tmp_path / "train", "wb") as f:
        pickle.dump({b"data": data, b"fine_labels": [5, 9, 11]}, f)
    c100 = Cifar100(data_file=str(tmp_path), mode="train")
    assert len(c100) == 3
    img, lbl = c100[2]
    assert img.shape == (3, 32, 32) and lbl == 11


def test_flowers_and_voc2012(tmp_path):
    import scipy.io

    from paddlepaddle_tpu.vision.datasets import VOC2012, Flowers

    jpg_dir = tmp_path / "jpg"
    jpg_dir.mkdir()
    for i in (1, 2, 3):
        _write_png(str(jpg_dir / f"image_{i:05d}.jpg"),
                   np.full((6, 6, 3), i, np.uint8))
    scipy.io.savemat(tmp_path / "imagelabels.mat",
                     {"labels": np.array([[4, 5, 6]])})
    scipy.io.savemat(tmp_path / "setid.mat",
                     {"trnid": np.array([[2, 3]]), "valid": np.array([[1]]),
                      "tstid": np.array([[1]])})
    ds = Flowers(data_file=str(jpg_dir),
                 label_file=str(tmp_path / "imagelabels.mat"),
                 setid_file=str(tmp_path / "setid.mat"), mode="train")
    assert len(ds) == 2
    img, lbl = ds[0]
    assert img[0, 0, 0] == 2 and lbl == 4  # image 2, label 5 -> 0-based 4

    voc = tmp_path / "VOC2012"
    (voc / "ImageSets" / "Segmentation").mkdir(parents=True)
    (voc / "JPEGImages").mkdir()
    (voc / "SegmentationClass").mkdir()
    (voc / "ImageSets" / "Segmentation" / "train.txt").write_text(
        "a\nb\n")
    for n in ("a", "b"):
        _write_png(str(voc / "JPEGImages" / f"{n}.jpg"),
                   np.zeros((5, 5, 3), np.uint8))
        _write_png(str(voc / "SegmentationClass" / f"{n}.png"),
                   np.ones((5, 5, 3), np.uint8))
    vds = VOC2012(data_file=str(voc), mode="train")
    assert len(vds) == 2
    img, seg = vds[0]
    assert img.shape == (5, 5, 3) and (seg == 1).all()
