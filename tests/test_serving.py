"""Serving engine: dynamic batching over KV-cache decode (inference L11).

Reference surface: the Predictor pool deployment layer
(paddle/fluid/inference/api/paddle_inference_api.h:229); the batching engine
itself exceeds the reference (its serving lives in external FastDeploy).
"""

import threading

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.inference import ServingEngine
from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM


def _model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(
        vocab_size=64, hidden_size=32, layers=2, heads=4, kv_heads=2,
        max_len=96))


def test_serving_batches_compatible_requests():
    m = _model()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, (8,)).astype(np.int32) for _ in range(6)]
    with ServingEngine(m, max_batch_size=4, max_wait_ms=200) as eng:
        futs = [eng.submit(p, max_new_tokens=6, temperature=0.0)
                for p in prompts]
        outs = [f.result(180) for f in futs]
    assert eng.stats["requests"] == 6
    assert eng.stats["batches"] < 6  # requests actually shared programs
    # greedy parity with a standalone single-prompt run
    ref = m.generate_cached(prompts[0][None, :], max_new_tokens=6,
                            temperature=0.0).numpy()[0]
    np.testing.assert_array_equal(outs[0], ref)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o[:8], p)  # echo prompt prefix


def test_serving_mixed_shapes_and_threads():
    """Incompatible requests (different prompt lengths) still complete; a
    multi-threaded client sees its own results."""
    m = _model()
    rng = np.random.default_rng(1)
    with ServingEngine(m, max_batch_size=4, max_wait_ms=50) as eng:
        results = {}

        def client(i, plen):
            p = rng.integers(0, 64, (plen,)).astype(np.int32)
            out = eng.generate(p, max_new_tokens=4, temperature=0.0,
                               timeout=180)
            results[i] = (p, out)

        threads = [threading.Thread(target=client, args=(i, 6 + (i % 2) * 4))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(200)
    assert len(results) == 4
    for i, (p, out) in results.items():
        np.testing.assert_array_equal(out[: len(p)], p)
        assert out.shape[0] == len(p) + 4


def test_serving_error_propagates():
    m = _model()
    with ServingEngine(m) as eng:
        # admission control rejects an unservable request AT SUBMIT (typed
        # RequestValidationError, still a ValueError) instead of queueing it
        with pytest.raises(ValueError, match="max_position_embeddings"):
            eng.submit(np.zeros((200,), np.int32), max_new_tokens=4)


def test_onnx_export_requires_input_spec():
    # the converter itself is covered by tests/test_onnx_export.py; here
    # just pin the contract that tracing needs example inputs
    import paddlepaddle_tpu.onnx as ponnx

    with pytest.raises(ValueError, match="input_spec"):
        ponnx.export(_model(), "/tmp/x.onnx")


def test_continuous_batching_ragged_parity():
    """N ragged requests (mixed prompt lengths, budgets, eos) through fewer
    slots: continuous batching must produce exactly the per-request greedy
    generate_cached outputs, with mid-flight admission (requests > slots)."""
    import numpy as np

    m = _model()
    rng = np.random.default_rng(0)
    specs = [(5, 8), (17, 4), (3, 12), (40, 6), (9, 8), (22, 3), (11, 5),
             (29, 7), (7, 9), (14, 4)]
    with ServingEngine(m, max_batch_size=4, decode_chunk=4) as eng:
        futs = []
        prompts = []
        for n, mx in specs:
            p = rng.integers(0, 128, (n,)).astype(np.int32)
            prompts.append((p, mx))
            futs.append(eng.submit(p, max_new_tokens=mx))
        outs = [f.result(300) for f in futs]
    for (p, mx), out in zip(prompts, outs):
        ref = m.generate_cached(p[None], max_new_tokens=mx,
                                temperature=0.0).numpy()[0]
        np.testing.assert_array_equal(out, ref)
    assert eng.stats["decode_tokens"] > 0


def test_continuous_batching_eos_mix():
    """Per-slot eos: requests with different eos ids share the decode
    program and each stops at its own token."""
    import numpy as np

    m = _model()
    rng = np.random.default_rng(1)
    with ServingEngine(m, max_batch_size=4, decode_chunk=4) as eng:
        p1 = rng.integers(0, 128, (6,)).astype(np.int32)
        p2 = rng.integers(0, 128, (11,)).astype(np.int32)
        f1 = eng.submit(p1, max_new_tokens=8, eos_token_id=3)
        f2 = eng.submit(p2, max_new_tokens=8, eos_token_id=7)
        o1, o2 = f1.result(300), f2.result(300)
    r1 = m.generate_cached(p1[None], max_new_tokens=8, temperature=0.0,
                           eos_token_id=3).numpy()[0]
    # engine keeps tokens up to and including eos, budget-trimmed like ref
    assert list(o1[:len(r1)]) == list(r1[:len(o1)])
    assert o2 is not None and len(o2) >= len(p2)


def test_predictor_pool_and_stream_variants():
    """PredictorPool (reference paddle_inference_api.h:229): one model
    load, per-slot handles, shared compiled program; stream.* collectives
    carry the sync_op/task contract."""
    import tempfile

    import numpy as np

    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.inference import Config, PredictorPool

    lin = paddle.nn.Linear(4, 2)
    with tempfile.TemporaryDirectory() as td:
        prefix = td + "/m"
        paddle.jit.save(lin, prefix,
                        input_spec=[paddle.static.InputSpec([2, 4], "float32")])
        pool = PredictorPool(Config(prefix), size=3)
        assert len(pool) == 3
        x = np.ones((2, 4), np.float32)
        outs = []
        for i in range(3):
            p = pool.retrive(i)
            h = p.get_input_handle(p.get_input_names()[0])
            h.copy_from_cpu(x)
            outs.append(p.run()[0])
        np.testing.assert_allclose(outs[0], outs[1])
        assert pool.retrive(0)._layer is pool.retrive(2)._layer

    import paddlepaddle_tpu.distributed as dist

    t = paddle.to_tensor(np.asarray([1.0], np.float32))
    task = dist.communication.stream.all_reduce(t, sync_op=False)
    assert not task.is_completed()
    task.wait()
    assert task.is_completed()


def test_static_mode_batching_still_works():
    """mode='static' (the equal-shape scheduler) kept as an option."""
    import numpy as np

    m = _model()
    rng = np.random.default_rng(5)
    with ServingEngine(m, mode="static", max_batch_size=4,
                       max_wait_ms=30.0) as eng:
        prompts = [rng.integers(0, 128, (7,)).astype(np.int32)
                   for _ in range(3)]
        futs = [eng.submit(p, max_new_tokens=4) for p in prompts]
        outs = [f.result(120) for f in futs]
    for p, o in zip(prompts, outs):
        ref = m.generate_cached(p[None], max_new_tokens=4,
                                temperature=0.0).numpy()[0]
        np.testing.assert_array_equal(o, ref)
    assert eng.stats["batches"] >= 1


def test_decode_engine_edges():
    """Boundary behavior: a request filling max_len exactly, a sampled
    (temperature + top_k) request completing with the right length, and
    the top_k cap validation — using the REAL GenerationRequest object."""
    from paddlepaddle_tpu.inference.decode_engine import BatchDecodeEngine
    from paddlepaddle_tpu.inference.serving import GenerationRequest

    m = _model()
    L = m.config.max_position_embeddings
    eng = BatchDecodeEngine(m, max_slots=2, max_len=L, chunk=4)

    def Req(ids, n, temp=0.0, top_k=0):
        return GenerationRequest(ids, n, temp, top_k, None)

    rng = np.random.default_rng(0)
    V = m.config.vocab_size
    # exactly fills max_len: prompt + new == L is admitted, +1 rejected
    fit = Req(rng.integers(0, V, (L - 4,)), 4)
    eng.serve([fit])
    out = fit.result.result(timeout=1)
    assert out is not None and len(out) == L
    over = Req(rng.integers(0, V, (L - 4,)), 5)
    with pytest.raises(ValueError, match="max_len"):
        eng._admit(over)

    # temperature sampling runs and respects the top_k filter cap
    warm = Req(rng.integers(0, V, (5,)), 6, temp=0.8, top_k=16)
    eng.serve([warm])
    assert len(warm.result.result(timeout=1)) == 11
    too_big = Req(rng.integers(0, V, (5,)), 2, temp=0.8,
                  top_k=BatchDecodeEngine.TOP_K_CAP + 1)
    with pytest.raises(ValueError, match="top_k"):
        eng._admit(too_big)
