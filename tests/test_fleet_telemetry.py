"""Fleet telemetry plane (observability/exporter.py, aggregate.py,
flight.py + tools/obsctl.py): per-rank HTTP exporters, rank-0 store-based
aggregation with a rank label per series, cross-rank chrome-trace merge,
and the crash flight recorder ("black box").

Reference surface: fleet-wide monitor stats + multi-worker profile merge;
MegaScale-style crash-surviving diagnostics.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.observability as obs
from paddlepaddle_tpu.observability import aggregate, exporter, flight
from paddlepaddle_tpu.observability.metrics import parse_prometheus_text

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBSCTL = os.path.join(_REPO, "tools", "obsctl.py")


@pytest.fixture
def clean_obs():
    """Observability + flight recorder + exporter singleton fully reset
    before AND after — no telemetry state may leak across suites."""
    obs.disable()
    obs.reset()
    flight.disable()
    exporter.stop()
    yield obs
    obs.disable()
    obs.reset()
    flight.disable()
    exporter.stop()


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---------------------------------------------------------------------------
# per-rank exporter
# ---------------------------------------------------------------------------

def test_exporter_serves_metrics_healthz_vars_trace(clean_obs):
    obs.enable(trace=True, metrics=True, watchdog_=False)
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    _ = paddle.add(x, x)
    with obs.RecordEvent("probe_region"):
        pass
    with exporter.TelemetryExporter(port=0) as e:
        status, body = _get(e.url("/metrics"))
        assert status == 200
        fams = parse_prometheus_text(body.decode())  # valid exposition
        assert "paddle_op_calls_total" in fams

        status, body = _get(e.url("/healthz"))
        assert status == 200
        health = json.loads(body)
        assert health["ok"] is True
        assert health["rank"] == 0 and health["world"] == 1
        assert health["obs"]["metrics"] is True
        assert health["obs"]["blackbox"] is False

        status, body = _get(e.url("/vars"))
        assert status == 200
        doc = json.loads(body)  # strict JSON (no Infinity), labeled rows
        rows = doc["paddle_op_calls_total"]
        assert any(r["labels"] == {"op": "add"} and r["value"] == 1
                   for r in rows)

        status, body = _get(e.url("/trace"))
        assert status == 200
        trace = json.loads(body)
        assert trace["displayTimeUnit"] == "ms"
        assert any(ev["name"] == "probe_region"
                   for ev in trace["traceEvents"])

        status, body = _get(e.url("/no/such/route"))
        assert status == 404
        assert "/metrics" in json.loads(body)["routes"]


def test_vars_stays_strict_json_with_nonfinite_observations(clean_obs):
    """A histogram that saw inf must not make /vars emit `Infinity` (which
    strict JSON parsers reject) — non-finite scalars become null."""
    obs.get_registry().histogram("paddle_degenerate_seconds",
                                 "probe").observe(float("inf"))
    obs.get_registry().gauge("paddle_degenerate_gauge",
                             "probe").set(float("nan"))
    with exporter.TelemetryExporter(port=0) as e:
        status, body = _get(e.url("/vars"))
        assert status == 200
        doc = json.loads(body.decode(), parse_constant=lambda c: (
            pytest.fail(f"non-strict JSON constant {c} in /vars")))
        (row,) = doc["paddle_degenerate_seconds"]
        assert row["value"]["sum"] is None
        assert row["value"]["min"] is None
        (grow,) = doc["paddle_degenerate_gauge"]
        assert grow["value"] is None


def test_exporter_health_providers_gate_the_503(clean_obs):
    with exporter.TelemetryExporter(port=0) as e:
        e.register_health("serving", lambda: {"ok": True, "state": "serving"})
        status, body = _get(e.url("/healthz"))
        assert status == 200
        assert json.loads(body)["providers"]["serving"]["state"] == "serving"

        e.register_health("serving", lambda: {"ok": False, "state": "open"})
        status, body = _get(e.url("/healthz"))
        assert status == 503
        assert json.loads(body)["ok"] is False

        def broken():
            raise RuntimeError("probe exploded")

        e.register_health("serving", broken)
        status, body = _get(e.url("/healthz"))
        assert status == 503
        assert "probe exploded" in json.loads(body)["providers"]["serving"]["error"]

        e.unregister_health("serving")
        status, _ = _get(e.url("/healthz"))
        assert status == 200


def test_exporter_falls_back_to_ephemeral_port_when_taken(clean_obs, capfd):
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    taken = blocker.getsockname()[1]
    try:
        with exporter.TelemetryExporter(port=taken) as e:
            assert e.port is not None and e.port != taken
            status, _ = _get(e.url("/healthz"))
            assert status == 200
    finally:
        blocker.close()
    assert "falling back" in capfd.readouterr().err


def test_serving_engine_registers_health_with_running_exporter(clean_obs):
    serving = pytest.importorskip("paddlepaddle_tpu.inference.serving")

    class _Out:
        def __init__(self, a):
            self._a = a

        def numpy(self):
            return self._a

    class FakeModel:
        def generate_cached(self, ids, max_new_tokens, **kw):
            return _Out(np.concatenate(
                [ids, np.zeros((ids.shape[0], max_new_tokens), np.int32)],
                axis=1))

    e = exporter.start(port=0)
    eng = serving.ServingEngine(FakeModel(), mode="static",
                                max_batch_size=2, max_wait_ms=5.0,
                                max_len=64)
    try:
        eng.submit(np.zeros((4,), np.int32), max_new_tokens=4).result(30)
        status, body = _get(e.url("/healthz"))
        assert status == 200
        prov = json.loads(body)["providers"]["serving"]
        assert prov["state"] == "serving" and prov["ok"] is True
    finally:
        eng.stop()
    # a deliberate stop unregisters: the process is not "unhealthy"
    status, body = _get(e.url("/healthz"))
    assert status == 200
    assert "serving" not in json.loads(body)["providers"]


# ---------------------------------------------------------------------------
# fleet aggregation (metric merge + trace merge)
# ---------------------------------------------------------------------------

_T0 = ('# HELP paddle_demo_total a demo counter\n'
       '# TYPE paddle_demo_total counter\n'
       'paddle_demo_total{op="add"} 3\n')
_T1 = ('# HELP paddle_demo_total a demo counter\n'
       '# TYPE paddle_demo_total counter\n'
       'paddle_demo_total{op="add"} 5\n'
       '# HELP paddle_demo_depth a demo gauge\n'
       '# TYPE paddle_demo_depth gauge\n'
       'paddle_demo_depth 2\n')


def test_merge_prometheus_texts_labels_every_sample_with_rank():
    merged = aggregate.merge_prometheus_texts({0: _T0, 1: _T1})
    assert 'paddle_demo_total{op="add",rank="0"} 3' in merged
    assert 'paddle_demo_total{op="add",rank="1"} 5' in merged
    assert 'paddle_demo_depth{rank="1"} 2' in merged
    # HELP/TYPE once per family, and the merge re-parses strictly
    assert merged.count("# TYPE paddle_demo_total counter") == 1
    fams = parse_prometheus_text(merged)
    assert {lab["rank"] for _, lab, _ in
            fams["paddle_demo_total"]["samples"]} == {"0", "1"}
    # an existing rank label is preserved, not clobbered
    pre = ('# HELP x_total h\n# TYPE x_total counter\n'
           'x_total{rank="9"} 1\n')
    assert 'rank="9"' in aggregate.merge_prometheus_texts({0: pre})


def test_merge_chrome_traces_one_pid_per_rank_with_clock_offsets():
    doc0 = {"traceEvents": [
        {"name": "step", "ph": "X", "ts": 1000, "dur": 10, "pid": 0,
         "tid": 1}], "displayTimeUnit": "ms"}
    doc1 = {"traceEvents": [
        {"name": "step", "ph": "X", "ts": 1000, "dur": 10, "pid": 0,
         "tid": 7}], "displayTimeUnit": "ms"}
    # rank 1's perf epoch started 2s "later" in wall terms: same wall
    # instant => its anchor (wall - perf) is 2s larger, shifting +2e6 us
    clocks = {0: {"wall": 100.0, "perf": 50.0},
              1: {"wall": 100.0, "perf": 48.0}}
    merged = aggregate.merge_chrome_traces({0: doc0, 1: doc1}, clocks)
    assert merged["displayTimeUnit"] == "ms"
    events = merged["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    assert {e["pid"] for e in spans} == {0, 1}
    by_pid = {e["pid"]: e for e in spans}
    assert by_pid[0]["ts"] == 1000
    assert by_pid[1]["ts"] == 1000 + 2_000_000
    assert by_pid[1]["tid"] == 7  # thread ids survive, only pid is rewritten
    meta = [e for e in events if e.get("ph") == "M"
            and e["name"] == "process_name"]
    assert {(m["pid"], m["args"]["name"]) for m in meta} == {
        (0, "rank 0"), (1, "rank 1")}
    json.loads(json.dumps(merged))  # Perfetto loads strict JSON


def test_fleet_publisher_and_rank0_merged_routes_over_store(clean_obs):
    """Two 'ranks' in one process: rank 1 publishes through a real TCPStore,
    rank 0's exporter serves the merged /metrics, /fleet/trace and
    /fleet/ranks — the in-process version of the 2-worker acceptance."""
    from paddlepaddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 0, is_master=True)
    trace1 = {"traceEvents": [{"name": "w1", "ph": "X", "ts": 5, "dur": 1,
                               "pid": 0, "tid": 2}], "displayTimeUnit": "ms"}
    pub = aggregate.FleetPublisher(
        store, rank=1, interval_s=0.1, text_fn=lambda: _T1,
        trace_fn=lambda: trace1).start()
    try:
        obs.enable(trace=True, metrics=True, watchdog_=False)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        _ = paddle.add(x, x)
        with exporter.TelemetryExporter(port=0) as e:
            aggregate.install_fleet_routes(e, store, world=2, local_rank=0)
            deadline = time.time() + 10
            fams = {}
            while time.time() < deadline:
                status, body = _get(e.url("/metrics"))
                assert status == 200
                fams = parse_prometheus_text(body.decode())
                if "paddle_demo_total" in fams:
                    break
                time.sleep(0.05)
            # rank 0's live series and rank 1's published series, labeled
            assert any(lab.get("rank") == "0" for _, lab, _ in
                       fams["paddle_op_calls_total"]["samples"])
            assert any(lab.get("rank") == "1" for _, lab, _ in
                       fams["paddle_demo_total"]["samples"])
            (reporting,) = [v for _, _, v in
                            fams["paddle_fleet_ranks_reporting"]["samples"]]
            assert reporting == 2

            # the unmerged per-rank view stays reachable
            status, body = _get(e.url("/metrics/local"))
            assert status == 200
            assert "rank=" not in body.decode()

            status, body = _get(e.url("/fleet/trace"))
            merged = json.loads(body)
            pids = {ev["pid"] for ev in merged["traceEvents"]}
            assert pids == {0, 1}
            assert any(ev.get("name") == "w1" and ev["pid"] == 1
                       for ev in merged["traceEvents"])

            status, body = _get(e.url("/fleet/ranks"))
            ranks = json.loads(body)["ranks"]
            assert ranks["1"]["published"] is True
            assert ranks["1"]["age_s"] is not None
    finally:
        pub.stop(final_publish=False)


def test_fleet_publisher_restart_and_runtime_trace_gate(clean_obs):
    from paddlepaddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 0, is_master=True)
    seen = []
    pub = aggregate.FleetPublisher(store, rank=5, interval_s=60,
                                   text_fn=lambda: seen.append(1) or _T0)
    pub.start()
    pub.stop(final_publish=False)
    n_after_stop = len(seen)
    # restartable: stop() must not leave the publisher thread stillborn
    pub.start()
    deadline = time.time() + 5
    while len(seen) <= n_after_stop and time.time() < deadline:
        time.sleep(0.02)
    assert len(seen) > n_after_stop, "restarted publisher never published"
    pub.stop(final_publish=False)

    # trace publication follows the RUNTIME tracing state (enable(trace=..)
    # without any PADDLE_OBS_TRACE env), not the env flag alone
    obs.enable(trace=True, metrics=False, watchdog_=False)
    with obs.RecordEvent("fleet_trace_probe"):
        pass
    aggregate.FleetPublisher(store, rank=6, interval_s=60,
                             text_fn=lambda: _T0).publish()
    assert store.check(aggregate.trace_key(6))
    doc = json.loads(store.get(aggregate.trace_key(6)))["trace"]
    assert any(ev["name"] == "fleet_trace_probe" for ev in doc["traceEvents"])
    obs.disable()
    aggregate.FleetPublisher(store, rank=7, interval_s=60,
                             text_fn=lambda: _T0).publish()
    assert not store.check(aggregate.trace_key(7))  # tracing off: no trace

    # an UNCHANGED ring is not re-serialized/re-shipped every interval
    # (each store request holds the client's wire mutex)
    obs.enable(trace=True, metrics=False, watchdog_=False)
    with obs.RecordEvent("dedup_probe"):
        pass
    set_keys = []
    orig_set = store.set
    store.set = lambda k, v: (set_keys.append(k), orig_set(k, v))[1]
    try:
        pub8 = aggregate.FleetPublisher(store, rank=8, interval_s=60,
                                        text_fn=lambda: _T0)
        tk = aggregate.trace_key(8)
        pub8.publish()
        pub8.publish()  # no new spans in between: trace skipped
        assert set_keys.count(tk) == 1
        with obs.RecordEvent("dedup_probe2"):
            pass
        pub8.publish()
        assert set_keys.count(tk) == 2  # ring changed: republished
    finally:
        store.set = orig_set


def test_two_engines_get_distinct_health_providers(clean_obs):
    """Two providers under one exporter must not clobber each other, and a
    guarded unregister only removes its own entry."""
    with exporter.TelemetryExporter(port=0) as e:
        fn_a = lambda: {"ok": True, "who": "a"}   # noqa: E731
        fn_b = lambda: {"ok": True, "who": "b"}   # noqa: E731
        name_a = e.register_health("serving", fn_a, unique=True)
        name_b = e.register_health("serving", fn_b, unique=True)
        assert name_a == "serving" and name_b == "serving-2"
        _, body = _get(e.url("/healthz"))
        providers = json.loads(body)["providers"]
        assert providers["serving"]["who"] == "a"
        assert providers["serving-2"]["who"] == "b"
        # stale guarded unregister (wrong fn) is a no-op
        e.unregister_health(name_b, fn=fn_a)
        _, body = _get(e.url("/healthz"))
        assert "serving-2" in json.loads(body)["providers"]
        e.unregister_health(name_b, fn=fn_b)
        _, body = _get(e.url("/healthz"))
        assert "serving-2" not in json.loads(body)["providers"]


# ---------------------------------------------------------------------------
# flight recorder (black box)
# ---------------------------------------------------------------------------

def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def test_flight_ring_is_bounded_and_dump_has_stacks(tmp_path, clean_obs):
    rec = flight.enable(str(tmp_path), capacity=16)
    for i in range(50):
        flight.record("probe", f"e{i}", i=i)
    assert len(rec.events()) == 16
    assert rec.events()[0]["name"] == "e34"  # oldest fell off
    path = flight.dump("unit_test")
    recs = _read_jsonl(path)
    head = recs[0]
    assert head["rec"] == "header" and head["reason"] == "unit_test"
    assert head["rank"] == 0 and head["world"] == 1
    events = [r for r in recs if r["rec"] == "event"]
    assert len(events) == 16
    assert events[-1]["name"] == "e49"
    (stacks,) = [r for r in recs if r["rec"] == "stacks"]
    mains = [t for t in stacks["threads"] if t["name"] == "MainThread"]
    assert mains and any("test_flight_ring" in fr
                         for fr in mains[0]["frames"])
    assert recs[-1]["rec"] == "end"


def test_flight_open_step_survives_ring_eviction(tmp_path, clean_obs):
    flight.enable(str(tmp_path), capacity=16)
    flight.record("step", "train_step", phase="begin", ordinal=7)
    for i in range(40):  # push the begin event out of the ring
        flight.record("noise", f"n{i}")
    recs = _read_jsonl(flight.dump("evicted"))
    (open_step,) = [r for r in recs if r["rec"] == "in_flight_step"]
    assert open_step["name"] == "train_step"
    assert open_step["data"]["ordinal"] == 7
    # a closed step is not in-flight
    flight.record("step", "train_step", phase="end", ordinal=7, ok=True)
    recs = _read_jsonl(flight.dump("closed"))
    assert not [r for r in recs if r["rec"] == "in_flight_step"]


def test_flight_excepthook_dumps_then_chains(tmp_path, clean_obs):
    prev_hook = sys.excepthook
    flight.enable(str(tmp_path), capacity=16)
    assert sys.excepthook is not prev_hook
    flight.record("step", "train_step", phase="begin", ordinal=1)
    chained = []
    saved = flight._prev_excepthook
    flight._prev_excepthook = lambda *a: chained.append(a)
    try:
        raise RuntimeError("boom for the black box")
    except RuntimeError:
        sys.excepthook(*sys.exc_info())
    finally:
        flight._prev_excepthook = saved
    assert chained, "the previous excepthook must still run"
    files = [f for f in os.listdir(tmp_path) if f.endswith(".jsonl")]
    assert len(files) == 1 and "unhandled_exception" in files[0]
    recs = _read_jsonl(os.path.join(tmp_path, files[0]))
    (exc,) = [r for r in recs if r["rec"] == "exception"]
    assert exc["type"] == "RuntimeError"
    assert "boom for the black box" in exc["value"]
    assert any(r["rec"] == "in_flight_step" for r in recs)
    flight.disable()
    assert sys.excepthook is prev_hook  # hooks restored


def test_runtime_seams_feed_the_flight_recorder(tmp_path, clean_obs):
    """step boundaries, retries, chaos injections, collective launches —
    the seams the ISSUE names — all land in the ring."""
    from paddlepaddle_tpu.distributed.watchdog import Watchdog
    from paddlepaddle_tpu.resilience import chaos
    from paddlepaddle_tpu.resilience.retry import RetryPolicy, call_with_retry

    rec = flight.enable(str(tmp_path), capacity=128)
    wd = Watchdog(timeout=60, abort=False)
    with wd.step("train_step"):
        pass
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 2:
            raise OSError("transient")
        return "ok"

    assert call_with_retry(flaky, policy=RetryPolicy(max_attempts=3,
                                                     base_delay=0.0),
                           sleep=lambda s: None) == "ok"
    chaos.configure("probe.seam:exc:x1")
    with pytest.raises(chaos.ChaosError):
        chaos.chaos_point("probe.seam")
    chaos.disable()
    grad = paddle.to_tensor(np.ones((4,), np.float32))
    paddle.distributed.all_reduce(grad)

    kinds = {(e["kind"], e["name"]) for e in rec.events()}
    assert ("step", "train_step") in kinds
    assert ("retry", "flaky") in kinds
    assert ("chaos", "probe.seam") in kinds
    assert ("collective", "all_reduce") in kinds
    steps = [e for e in rec.events() if e["kind"] == "step"]
    assert [e["data"]["phase"] for e in steps] == ["begin", "end"]
    assert steps[1]["data"]["ok"] is True

    # an exc injection AT the step seam aborts __enter__ before __exit__
    # exists — the flight span must still close, or a later unrelated dump
    # reports a phantom in-flight step
    chaos.configure("step:exc:x1")
    with pytest.raises(chaos.ChaosError):
        with wd.step("doomed_step"):
            pytest.fail("step body must not run when the seam raises")
    chaos.disable()
    recs = _read_jsonl(flight.dump("after_step_exc"))
    assert not [r for r in recs if r["rec"] == "in_flight_step"]
    doomed = [e for e in rec.events() if e["kind"] == "step"
              and e["name"] == "doomed_step"]
    assert [e["data"]["phase"] for e in doomed] == ["begin", "end"]
    assert doomed[1]["data"]["ok"] is False


def test_watchdog_timeout_dump_survives_via_flight(tmp_path, clean_obs):
    """Satellite: the step-watchdog timeout report is persisted by the
    flight recorder (not only stderr) and carries all-thread stacks."""
    from paddlepaddle_tpu.distributed.watchdog import Watchdog

    flight.enable(str(tmp_path), capacity=64)
    fired = threading.Event()
    wd = Watchdog(timeout=0.05, poll_interval=0.01, abort=False,
                  on_timeout=lambda *a: fired.set()).start()
    try:
        with wd.step("stalling_step"):
            assert fired.wait(5), "watchdog did not fire"
            time.sleep(0.05)  # let _dump finish writing
    finally:
        wd.stop()
    files = [f for f in os.listdir(tmp_path) if "step_timeout" in f]
    assert files, "timeout must leave a black box"
    recs = _read_jsonl(os.path.join(tmp_path, files[0]))
    (ev,) = [r for r in recs if r["rec"] == "event"
             and r["kind"] == "watchdog_timeout"]
    assert ev["name"] == "stalling_step"
    assert ev["data"]["elapsed_s"] >= 0.05
    (open_step,) = [r for r in recs if r["rec"] == "in_flight_step"]
    assert open_step["name"] == "stalling_step"
    (stacks,) = [r for r in recs if r["rec"] == "stacks"]
    assert len(stacks["threads"]) >= 2  # main + watchdog monitor at least
    all_frames = "".join(fr for t in stacks["threads"]
                         for fr in t["frames"])
    assert "stalling_step" in all_frames or "wait" in all_frames


def test_breaker_open_flushes_black_box(tmp_path, clean_obs):
    serving = pytest.importorskip("paddlepaddle_tpu.inference.serving")

    class _Sick:
        def generate_cached(self, ids, max_new_tokens, **kw):
            raise RuntimeError("decode keeps failing")

    flight.enable(str(tmp_path), capacity=64)
    eng = serving.ServingEngine(_Sick(), mode="static", max_batch_size=1,
                                max_wait_ms=1.0, max_len=64,
                                breaker_threshold=2)
    try:
        for _ in range(2):
            with pytest.raises(RuntimeError):
                eng.submit(np.zeros((4,), np.int32),
                           max_new_tokens=4).result(30)
        deadline = time.time() + 5
        while time.time() < deadline and not any(
                "breaker_open" in f for f in os.listdir(tmp_path)):
            time.sleep(0.05)
    finally:
        eng.stop()
    files = [f for f in os.listdir(tmp_path) if "breaker_open" in f]
    assert files, "an opening breaker must flush the flight recorder"
    recs = _read_jsonl(os.path.join(tmp_path, files[0]))
    transitions = [r for r in recs if r["rec"] == "event"
                   and r["kind"] == "breaker"]
    assert any(t["data"]["to"] == "open" for t in transitions)


# ---------------------------------------------------------------------------
# obsctl
# ---------------------------------------------------------------------------

def _load_obsctl():
    import importlib.util

    spec = importlib.util.spec_from_file_location("obsctl", _OBSCTL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obsctl_scrape_and_aggregate_over_http(clean_obs, capsys):
    obsctl = _load_obsctl()
    obs.enable(trace=False, metrics=True, watchdog_=False)
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    _ = paddle.add(x, x)
    with exporter.TelemetryExporter(port=0) as e:
        assert obsctl.main(["scrape", f"127.0.0.1:{e.port}"]) == 0
        assert "paddle_op_calls_total" in capsys.readouterr().out
        assert obsctl.main(["aggregate", f"127.0.0.1:{e.port}",
                            e.url()]) == 0
        captured = capsys.readouterr()
        fams = parse_prometheus_text(captured.out)
        # both targets are the same rank-0 exporter: colliding self-reported
        # ranks fall back to list-position labels (with a warning) instead
        # of one target silently clobbering the other
        assert "labeling targets by list position" in captured.err
        assert {lab["rank"] for _, lab, _ in
                fams["paddle_op_calls_total"]["samples"]} == {"0", "1"}
        # a dead target is skipped, not fatal to the merge
        assert obsctl.main(["aggregate", "127.0.0.1:9",
                            f"127.0.0.1:{e.port}", "--timeout", "2"]) == 0
        captured = capsys.readouterr()
        assert "skipping" in captured.err
        assert "paddle_op_calls_total" in captured.out


def test_obsctl_merge_trace_writes_perfetto_file(tmp_path, capsys):
    obsctl = _load_obsctl()
    for r in (0, 1):
        with open(tmp_path / f"trace{r}.json", "w") as f:
            json.dump({"traceEvents": [
                {"name": f"op{r}", "ph": "X", "ts": 10, "dur": 2,
                 "pid": 0, "tid": 1}], "displayTimeUnit": "ms"}, f)
    out = str(tmp_path / "merged.json")
    assert obsctl.main(["merge-trace", "-o", out,
                        str(tmp_path / "trace0.json"),
                        str(tmp_path / "trace1.json")]) == 0
    with open(out) as f:
        merged = json.load(f)
    assert {e["pid"] for e in merged["traceEvents"]
            if e.get("ph") == "X"} == {0, 1}


def test_obsctl_blackbox_tail_renders_newest_dump(tmp_path, clean_obs):
    flight.enable(str(tmp_path), capacity=32)
    flight.record("step", "train_step", phase="begin", ordinal=3)
    flight.record("retry", "store.get", attempt=1)
    flight.dump("drill")
    # obsctl blackbox tail is stdlib-only: run it as a real subprocess
    out = subprocess.run(
        [sys.executable, _OBSCTL, "blackbox", "tail", "--dir",
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "reason=drill" in out.stdout
    assert "step" in out.stdout and "train_step" in out.stdout
    assert "retry" in out.stdout
    assert "IN-FLIGHT STEP" in out.stdout
    assert "stacks:" in out.stdout


def test_obsctl_scrape_dead_target_is_one_line_error(tmp_path):
    out = subprocess.run(
        [sys.executable, _OBSCTL, "scrape", "127.0.0.1:9", "--timeout", "2"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 1
    assert "Traceback" not in out.stderr
    assert "127.0.0.1:9" in out.stderr


def test_obsctl_blackbox_tail_empty_dir(tmp_path):
    out = subprocess.run(
        [sys.executable, _OBSCTL, "blackbox", "tail", "--dir",
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 1
    assert "no black-box dumps" in out.stderr


# ---------------------------------------------------------------------------
# end-to-end drills (slow: real distributed.launch subprocesses)
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_FLEET_WORKER = r"""
import os, sys, time
sys.path.insert(0, os.environ["REPO_DIR"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PADDLE_OBS_TRACE", "1")   # publish traces too
import numpy as np
import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.observability as obs

rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
stop_file = os.environ["DRILL_STOP"]
x = paddle.to_tensor(np.ones((2, 2), np.float32))
deadline = time.time() + 120
while not os.path.exists(stop_file) and time.time() < deadline:
    _ = paddle.add(x, x)      # keeps per-rank op counters moving
    time.sleep(0.05)
print(f"FLEET_RANK{rank}_DONE", flush=True)
"""


@pytest.mark.slow
def test_launch_two_workers_rank0_serves_fleet_metrics_and_trace(tmp_path):
    """Acceptance: distributed.launch with 2 workers -> rank 0's merged
    /metrics has per-rank-labeled series from BOTH workers; the merged
    trace is Perfetto-valid JSON with one pid per rank."""
    script = tmp_path / "worker.py"
    script.write_text(_FLEET_WORKER)
    stop_file = str(tmp_path / "stop")
    base_port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               REPO_DIR=_REPO, DRILL_STOP=stop_file,
               PADDLE_OBS_PUBLISH_INTERVAL_S="0.3",
               # env-based enablement on the LAUNCHER too: its own
               # import-time exporter binds base_port first, and launch()
               # must release it for the real rank 0 (regression: launcher
               # squatting the deterministic port)
               PADDLE_OBS_EXPORT="1", PADDLE_OBS_PORT=str(base_port))
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddlepaddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--obs_export",
         "--obs_port", str(base_port), str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=_REPO)
    try:
        fams = {}
        deadline = time.time() + 120
        while time.time() < deadline:
            assert proc.poll() is None, proc.stdout.read()[-3000:]
            try:
                status, body = _get(
                    f"http://127.0.0.1:{base_port}/metrics", timeout=5)
            except (OSError, urllib.error.URLError):
                time.sleep(0.3)
                continue
            if status != 200:
                time.sleep(0.3)
                continue
            fams = parse_prometheus_text(body.decode())
            samples = fams.get("paddle_op_calls_total", {}).get("samples", [])
            if {lab.get("rank") for _, lab, _ in samples} >= {"0", "1"}:
                break
            time.sleep(0.3)
        samples = fams.get("paddle_op_calls_total", {}).get("samples", [])
        ranks = {lab.get("rank") for _, lab, _ in samples}
        assert ranks >= {"0", "1"}, f"merged series from {ranks}, want both"
        (reporting,) = [v for _, _, v in
                        fams["paddle_fleet_ranks_reporting"]["samples"]]
        assert reporting == 2

        # per-rank exporters answer on base+rank too
        status, body = _get(f"http://127.0.0.1:{base_port + 1}/healthz")
        assert status == 200 and json.loads(body)["rank"] == 1

        status, body = _get(f"http://127.0.0.1:{base_port}/fleet/trace",
                            timeout=30)
        assert status == 200
        merged = json.loads(body)  # Perfetto-valid strict JSON
        assert merged["displayTimeUnit"] == "ms"
        span_pids = {ev["pid"] for ev in merged["traceEvents"]
                     if ev.get("ph") == "X"}
        assert span_pids == {0, 1}, f"one pid per rank, got {span_pids}"
    finally:
        with open(stop_file, "w") as f:
            f.write("stop")
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert rc == 0, proc.stdout.read()[-3000:]


_KILL_WORKER = r"""
import os, sys, time
sys.path.insert(0, os.environ["REPO_DIR"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.distributed.watchdog import Watchdog

wd = Watchdog(timeout=300, abort=False)
x = paddle.to_tensor(np.ones((2, 2), np.float32))
for step in range(10):
    with wd.step("train_step"):   # chaos seam "step" + flight step events
        _ = paddle.add(x, x)
print("KILL_WORKER_SURVIVED", flush=True)
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_kill_leaves_blackbox_and_obsctl_renders_it(tmp_path):
    """Acceptance: PADDLE_CHAOS_POINTS=step:kill:@N leaves a black-box
    JSONL whose final records include the in-flight step event and thread
    stacks; `obsctl blackbox tail` renders it."""
    script = tmp_path / "worker.py"
    script.write_text(_KILL_WORKER)
    bb_dir = str(tmp_path / "blackbox")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               REPO_DIR=_REPO,
               PADDLE_OBS_BLACKBOX="1",
               PADDLE_OBS_BLACKBOX_DIR=bb_dir,
               PADDLE_CHAOS_POINTS="step:kill:@4:77",
               PADDLE_CHAOS_SEED="1234")
    out = subprocess.run(
        [sys.executable, "-m", "paddlepaddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", "0", str(script)],
        capture_output=True, text=True, timeout=300, env=env, cwd=_REPO)
    assert out.returncode == 77, (out.returncode, out.stderr[-2000:])
    assert "KILL_WORKER_SURVIVED" not in out.stdout

    files = [f for f in os.listdir(bb_dir) if f.endswith(".jsonl")]
    assert len(files) == 1, files
    assert "chaos_kill" in files[0]
    recs = _read_jsonl(os.path.join(bb_dir, files[0]))
    assert recs[0]["reason"].startswith("chaos_kill")
    # the in-flight step: step 4 began (flight event) but never ended —
    # surfaced both as the last step event and as an in_flight_step record
    step_events = [r for r in recs if r["rec"] == "event"
                   and r["kind"] == "step"]
    assert step_events[-1]["data"] == {"phase": "begin", "ordinal": 4}
    (open_step,) = [r for r in recs if r["rec"] == "in_flight_step"]
    assert open_step["data"]["ordinal"] == 4
    (chaos_ev,) = [r for r in recs if r["rec"] == "event"
                   and r["kind"] == "chaos"]
    assert chaos_ev["name"] == "step" and chaos_ev["data"]["mode"] == "kill"
    (stacks,) = [r for r in recs if r["rec"] == "stacks"]
    assert any(t["name"] == "MainThread" for t in stacks["threads"])

    tail = subprocess.run(
        [sys.executable, _OBSCTL, "blackbox", "tail", "--dir", bb_dir],
        capture_output=True, text=True, timeout=60)
    assert tail.returncode == 0, tail.stderr
    assert "reason=chaos_kill" in tail.stdout
    assert "IN-FLIGHT STEP" in tail.stdout
    assert "stacks:" in tail.stdout
