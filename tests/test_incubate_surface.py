"""Incubate surface: LookAhead/ModelAverage, fused layers, graph ops,
Jacobian/Hessian objects, and namespace closure vs the reference."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle

rng = np.random.default_rng(0)
X = rng.standard_normal((8, 4)).astype(np.float32)


def test_lookahead_trains():
    lin = paddle.nn.Linear(4, 1)
    inner = paddle.optimizer.Adam(learning_rate=0.02,
                                  parameters=lin.parameters())
    la = paddle.incubate.LookAhead(inner, alpha=0.5, k=2)
    first = last = None
    for _ in range(6):
        loss = ((lin(X) - 1.0) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()
        first = first if first is not None else float(loss.numpy())
        last = float(loss.numpy())
    assert last < first


def test_model_average_apply_restore():
    lin = paddle.nn.Linear(4, 1)
    ma = paddle.incubate.ModelAverage(parameters=lin.parameters())
    w0 = lin.weight.numpy().copy()
    ma.step()
    lin.weight._replace_data(lin.weight._data * 2)
    ma.step()
    ma.apply()
    avg = lin.weight.numpy().copy()
    np.testing.assert_allclose(avg, 1.5 * w0, rtol=1e-6)
    ma.restore()
    np.testing.assert_allclose(lin.weight.numpy(), 2 * w0, rtol=1e-6)


def test_fused_layers_forward():
    fl = paddle.incubate.nn.FusedLinear(4, 3)
    assert fl(X).shape == [8, 3]
    src = rng.standard_normal((2, 5, 8)).astype(np.float32)
    enc = paddle.incubate.nn.FusedTransformerEncoderLayer(
        8, 2, 16, dropout_rate=0.0)
    assert enc(src).shape == [2, 5, 8]
    mt = paddle.incubate.nn.FusedMultiTransformer(8, 2, 16, num_layers=2)
    mt.eval()
    assert mt(src).shape == [2, 5, 8]
    np.testing.assert_allclose(
        paddle.incubate.nn.functional.fused_matmul_bias(
            np.ones((2, 3), np.float32), np.ones((3, 4), np.float32),
            np.ones(4, np.float32)).numpy(), 4.0)


def test_softmax_mask_fuse_ops():
    a = rng.standard_normal((1, 1, 4, 4)).astype(np.float32)
    sm = paddle.incubate.softmax_mask_fuse_upper_triangle(a).numpy()
    assert np.allclose(np.triu(np.asarray(sm)[0, 0], 1), 0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sm).sum(-1), 1.0, rtol=1e-5)
    m = np.where(np.eye(4, dtype=bool), 0.0, -1e30).astype(np.float32)
    sm2 = paddle.incubate.softmax_mask_fuse(a, m[None, None]).numpy()
    np.testing.assert_allclose(np.asarray(sm2)[0, 0],
                               np.eye(4), atol=1e-6)


def test_graph_ops():
    x = np.eye(3, dtype=np.float32)
    src, dst = np.array([0, 1, 2], np.int64), np.array([1, 2, 0], np.int64)
    out = paddle.incubate.graph_send_recv(x, src, dst).numpy()
    expect = np.zeros_like(x)
    for s, d in zip(src, dst):
        expect[d] += x[s]
    np.testing.assert_allclose(out, expect)

    # CSC graph: 0->1,2  1->2  2->0 (cols = dst)
    row = np.array([2, 0, 0, 1], np.int64)
    colptr = np.array([0, 1, 2, 4], np.int64)
    nbrs, counts = paddle.incubate.graph_sample_neighbors(
        row, colptr, np.array([2], np.int64))
    assert sorted(nbrs.numpy().tolist()) == [0, 1]
    assert counts.numpy().tolist() == [2]

    rsrc, rdst, keys = paddle.incubate.graph_reindex(
        np.array([5, 9], np.int64), np.array([9, 7, 5], np.int64),
        np.array([2, 1], np.int64))
    assert keys.numpy().tolist() == [5, 9, 7]
    assert rdst.numpy().tolist() == [0, 0, 1]


def test_varlen_memory_efficient_attention():
    q = rng.standard_normal((2, 2, 6, 8)).astype(np.float32)
    out = paddle.incubate.nn.functional.\
        variable_length_memory_efficient_attention(
            q, q, q, np.array([4, 6], np.int32), np.array([4, 6], np.int32))
    assert out.shape == [2, 2, 6, 8]
    o = out.numpy()
    np.testing.assert_allclose(o[0, :, 4:], 0.0)  # padding rows stay zero


def test_jacobian_hessian_objects():
    jac = paddle.incubate.autograd.Jacobian(
        lambda a: (a ** 2).sum(), np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(np.asarray(jac.numpy()), [2.0, 4.0])
    hes = paddle.incubate.autograd.Hessian(
        lambda a: (a ** 2).sum(), np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(np.asarray(hes.numpy()), 2 * np.eye(2))
    g = paddle.incubate.autograd.forward_grad(
        lambda a: a * 3.0, np.array([1.0], np.float32))
    np.testing.assert_allclose(np.asarray(g.numpy()
                                          if hasattr(g, "numpy") else g), 3.0)


def test_incubate_namespaces_closed():
    import os
    import re

    if not os.path.exists("/root/reference"):
        pytest.skip("reference tree not present")
    for sub in ["", "/nn", "/nn/functional", "/autograd"]:
        path = f"/root/reference/python/paddle/incubate{sub}/__init__.py"
        ref = set(re.findall(r"'(\w+)'", open(path).read()))
        mod = paddle.incubate
        for part in sub.strip("/").split("/"):
            if part:
                mod = getattr(mod, part)
        missing = sorted(n for n in ref
                         if not hasattr(mod, n) and not n.startswith("_"))
        assert missing == [], f"incubate{sub}: {missing}"


def test_asp_prune_and_sparsity_guarantee():
    """2:4 structured sparsity (reference python/paddle/incubate/asp):
    prune_model halves density with the n:m invariant, and a decorated
    optimizer keeps pruned coordinates at zero across real train steps."""
    import numpy as np

    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.incubate import asp

    rng = np.random.default_rng(0)
    lin = paddle.nn.Linear(8, 8)
    lin.weight.set_value(rng.standard_normal((8, 8)).astype(np.float32))
    opt = asp.decorate(paddle.optimizer.SGD(learning_rate=0.1,
                                            parameters=lin.parameters()))
    asp.prune_model(lin)
    w = lin.weight.numpy()
    assert abs(asp.calculate_density(w) - 0.5) < 1e-6
    assert asp.check_mask_1d(w)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    for _ in range(3):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w2 = lin.weight.numpy()
    assert asp.check_mask_1d(w2), "mask not preserved through steps"
    assert abs(asp.calculate_density(w2) - 0.5) < 0.01
    assert not np.allclose(w, w2)      # training actually moved the weights

    # conv weights (out, in, kh, kw): n:m over the flattened trailing dims
    conv = paddle.nn.Conv2D(4, 8, 3)
    conv.weight.set_value(rng.standard_normal((8, 4, 3, 3)).astype(np.float32))
    asp.prune_model(conv)
    cw = conv.weight.numpy()
    assert abs(asp.calculate_density(cw) - 0.5) < 0.03, asp.calculate_density(cw)
    assert asp.check_mask_1d(cw.reshape(8, -1))


# ---- round-4 serving-attention closure (mmha / blha) -----------------------


def _np_sdpa(q, K, V, add_mask=None):
    """[H,D] query vs [H,L,D] keys -> [H,D], fp32 numpy oracle."""
    import numpy as np

    s = (q[:, None, :] * K).sum(-1) / np.sqrt(q.shape[-1])   # [H, L]
    if add_mask is not None:
        s = s + add_mask
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(-1, keepdims=True)
    return (p[:, :, None] * V).sum(1)


def test_blha_get_max_len():
    import numpy as np

    import paddlepaddle_tpu as paddle

    f = paddle.incubate.nn.functional
    enc = paddle.to_tensor(np.array([3, 0, 7], np.int32))
    dec = paddle.to_tensor(np.array([5, 2, 0], np.int32))
    me, md = f.blha_get_max_len(enc, dec, paddle.to_tensor(np.ones(3)))
    assert int(me.numpy()[0]) == 7 and int(md.numpy()[0]) == 5


def test_masked_multihead_attention_oracle():
    import numpy as np

    import paddlepaddle_tpu as paddle

    f = paddle.incubate.nn.functional
    rng = np.random.default_rng(3)
    bsz, H, D, max_seq = 2, 4, 8, 16
    x = rng.standard_normal((bsz, 3 * H * D)).astype(np.float32)
    bias = rng.standard_normal((3, H, D)).astype(np.float32)
    cache = rng.standard_normal((2, bsz, H, max_seq, D)).astype(np.float32)
    lens = np.array([[5], [9]], np.int32)      # write positions
    src_mask = rng.standard_normal((bsz, 1, 1, 10)).astype(np.float32)

    out, cache_out = f.masked_multihead_attention(
        paddle.to_tensor(x), paddle.to_tensor(cache.copy()),
        bias=paddle.to_tensor(bias), src_mask=paddle.to_tensor(src_mask),
        sequence_lengths=paddle.to_tensor(lens))
    out, cache_out = out.numpy(), cache_out.numpy()

    for b in range(bsz):
        L = int(lens[b, 0])
        qkv = x[b].reshape(3, H, D) + bias
        ref_c = cache.copy()
        ref_c[0, b, :, L] = qkv[1]
        ref_c[1, b, :, L] = qkv[2]
        np.testing.assert_allclose(cache_out[:, b], ref_c[:, b], rtol=1e-5)
        K = ref_c[0, b, :, :L + 1]
        V = ref_c[1, b, :, :L + 1]
        ref = _np_sdpa(qkv[0], K, V, add_mask=src_mask[b, 0, 0, :L + 1])
        np.testing.assert_allclose(out[b].reshape(H, D), ref, rtol=2e-4,
                                   atol=2e-5)


def test_block_multihead_attention_mixed_batch_gqa():
    """One prefill sequence + one decode sequence through the paged cache,
    GQA (kv_H=2, H=4), checked against a dense numpy oracle per sequence."""
    import numpy as np

    import paddlepaddle_tpu as paddle

    f = paddle.incubate.nn.functional
    rng = np.random.default_rng(5)
    H, kv_H, D, bs = 4, 2, 8, 4
    max_blocks, blocks_per_seq = 8, 3

    # seq 0: prefill 5 tokens; seq 1: decode with past=3, 1 new token
    enc = np.array([[5], [0]], np.int32)
    dec = np.array([[0], [3]], np.int32)
    this = np.array([[5], [1]], np.int32)
    cu_q = np.array([0, 5, 6], np.int32)
    btab = np.array([[0, 1, -1], [4, 5, -1]], np.int32)

    kc = rng.standard_normal((max_blocks, kv_H, bs, D)).astype(np.float32)
    vc = rng.standard_normal((max_blocks, kv_H, bs, D)).astype(np.float32)
    tok = 6
    qkv = rng.standard_normal((tok, (H + 2 * kv_H) * D)).astype(np.float32)

    out, _, kc_out, vc_out = f.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kc.copy()),
        paddle.to_tensor(vc.copy()), paddle.to_tensor(enc),
        paddle.to_tensor(dec), paddle.to_tensor(this),
        paddle.to_tensor(np.zeros(tok, np.int32)),
        paddle.to_tensor(np.zeros(2, np.int32)),
        paddle.to_tensor(cu_q), paddle.to_tensor(cu_q),
        paddle.to_tensor(btab), block_size=bs)
    out = out.numpy()
    kc_out, vc_out = kc_out.numpy(), vc_out.numpy()

    group = H // kv_H
    for b, (n, past) in enumerate([(5, 0), (1, 3)]):
        rows = qkv[cu_q[b]:cu_q[b] + n]
        q = rows[:, :H * D].reshape(n, H, D)
        k = rows[:, H * D:(H + kv_H) * D].reshape(n, kv_H, D)
        v = rows[:, (H + kv_H) * D:].reshape(n, kv_H, D)
        ref_kc, ref_vc = kc.copy(), vc.copy()
        for i, p in enumerate(range(past, past + n)):
            ref_kc[btab[b, p // bs], :, p % bs] = k[i]
            ref_vc[btab[b, p // bs], :, p % bs] = v[i]
        L = past + n
        K = np.concatenate([ref_kc[btab[b, j]] for j in range((L + bs - 1) // bs)],
                           axis=1)[:, :L]          # [kv_H, L, D]
        V = np.concatenate([ref_vc[btab[b, j]] for j in range((L + bs - 1) // bs)],
                           axis=1)[:, :L]
        for i in range(n):
            pos = past + i
            qi = q[i].reshape(kv_H, group, D)
            ref = np.zeros((kv_H, group, D), np.float32)
            for kh in range(kv_H):
                # causality = truncating keys to [0, pos]
                ref[kh] = _np_sdpa(qi[kh], np.repeat(K[kh][None, :pos + 1], group, 0),
                                   np.repeat(V[kh][None, :pos + 1], group, 0))
            np.testing.assert_allclose(
                out[cu_q[b] + i].reshape(H, D), ref.reshape(H, D),
                rtol=2e-4, atol=2e-5, err_msg=f"seq {b} tok {i}")
        # the written pages match
        for j in range((L + bs - 1) // bs):
            np.testing.assert_allclose(kc_out[btab[b, j]], ref_kc[btab[b, j]],
                                       rtol=1e-6)


def test_mmha_rotary_matches_manual_rotation():
    """rotary_tensor layout per the reference kernel's read pattern:
    flat [cos(bsz*D) | sin(bsz*D)], current position only, full D."""
    import numpy as np

    import paddlepaddle_tpu as paddle

    f = paddle.incubate.nn.functional
    rng = np.random.default_rng(9)
    bsz, H, D, max_seq = 1, 1, 8, 4
    x = rng.standard_normal((bsz, 3 * H * D)).astype(np.float32)
    theta = rng.uniform(0, np.pi, D // 2).astype(np.float32)
    cos = np.repeat(np.cos(theta), 2)[None, :]           # [bsz, D] paired
    sin = np.repeat(np.sin(theta), 2)[None, :]
    rt = np.concatenate([cos.ravel(), sin.ravel()])
    cache = np.zeros((2, bsz, H, max_seq, D), np.float32)

    _, cache_out = f.masked_multihead_attention(
        paddle.to_tensor(x), paddle.to_tensor(cache),
        sequence_lengths=paddle.to_tensor(np.zeros((1, 1), np.int32)),
        rotary_tensor=paddle.to_tensor(rt), rotary_emb_dims=1)
    k = x.reshape(3, H, D)[1][0]
    ref = np.empty(D, np.float32)
    c, s = cos[0, 0::2], sin[0, 0::2]
    ref[0::2] = k[0::2] * c - k[1::2] * s
    ref[1::2] = k[1::2] * c + k[0::2] * s
    np.testing.assert_allclose(cache_out.numpy()[0, 0, 0, 0], ref,
                               rtol=1e-5, atol=1e-6)


def test_blha_rope_layout_and_rotation():
    """rope_emb in the reference layout [2, bsz, max_seq, 1, D/2]: cache
    keys come out rotated per-position; the transposed singleton layout
    normalizes identically; a wrong trailing dim raises."""
    import numpy as np

    import paddlepaddle_tpu as paddle

    f = paddle.incubate.nn.functional
    rng = np.random.default_rng(13)
    H = kv_H = 1
    D, bs, S = 8, 4, 4
    qkv = rng.standard_normal((2, 3 * D)).astype(np.float32)
    theta = rng.uniform(0, np.pi, (S, D // 2)).astype(np.float32)
    rope = np.stack([np.cos(theta), np.sin(theta)])[:, None, :, None, :]

    def run(r):
        return f.block_multihead_attention(
            paddle.to_tensor(qkv),
            paddle.to_tensor(np.zeros((2, kv_H, bs, D), np.float32)),
            paddle.to_tensor(np.zeros((2, kv_H, bs, D), np.float32)),
            paddle.to_tensor(np.array([[2]], np.int32)),
            paddle.to_tensor(np.array([[0]], np.int32)),
            paddle.to_tensor(np.array([[2]], np.int32)),
            paddle.to_tensor(np.zeros(2, np.int32)),
            paddle.to_tensor(np.zeros(1, np.int32)),
            paddle.to_tensor(np.array([0, 2], np.int32)),
            paddle.to_tensor(np.array([0, 2], np.int32)),
            paddle.to_tensor(np.array([[0, 1]], np.int32)),
            rope_emb=paddle.to_tensor(r.astype(np.float32)), block_size=bs)

    _, _, kc_out, _ = run(rope)
    k = qkv[:, D:2 * D]
    for t in range(2):
        c, s = np.cos(theta[t]), np.sin(theta[t])
        ref = np.empty(D, np.float32)
        ref[0::2] = k[t, 0::2] * c - k[t, 1::2] * s
        ref[1::2] = k[t, 1::2] * c + k[t, 0::2] * s
        np.testing.assert_allclose(kc_out.numpy()[0, 0, t], ref, rtol=1e-5,
                                   atol=1e-6)
    # transposed singleton layout gives the same result
    _, _, kc2, _ = run(np.transpose(rope, (0, 1, 3, 2, 4)))
    np.testing.assert_allclose(kc2.numpy(), kc_out.numpy(), rtol=1e-6)
    with pytest.raises(ValueError, match="rope_emb"):
        run(rope[..., :3])


def test_serving_attention_quant_rejected():
    import numpy as np

    import paddlepaddle_tpu as paddle

    f = paddle.incubate.nn.functional
    with pytest.raises(NotImplementedError, match="quant"):
        f.masked_multihead_attention(
            paddle.to_tensor(np.zeros((1, 3 * 2 * 4), np.float32)),
            paddle.to_tensor(np.zeros((2, 1, 2, 8, 4), np.float32)),
            out_scale=0.5)
    zeros = lambda *s: paddle.to_tensor(np.zeros(s, np.float32))
    i32 = lambda *s: paddle.to_tensor(np.zeros(s, np.int32))
    with pytest.raises(NotImplementedError, match="quant"):
        f.block_multihead_attention(
            zeros(1, 3 * 2 * 4), zeros(2, 2, 4, 4), zeros(2, 2, 4, 4),
            i32(1, 1), i32(1, 1), i32(1, 1), i32(1), i32(1),
            i32(2), i32(2), i32(1, 2), block_size=4,
            cache_k_quant_scales=zeros(2))
    with pytest.raises(ValueError, match="block_size"):
        f.block_multihead_attention(
            zeros(1, 3 * 2 * 4), zeros(2, 2, 4, 4), zeros(2, 2, 4, 4),
            i32(1, 1), i32(1, 1), i32(1, 1), i32(1), i32(1),
            i32(2), i32(2), i32(1, 2), block_size=128)
