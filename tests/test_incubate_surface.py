"""Incubate surface: LookAhead/ModelAverage, fused layers, graph ops,
Jacobian/Hessian objects, and namespace closure vs the reference."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle

rng = np.random.default_rng(0)
X = rng.standard_normal((8, 4)).astype(np.float32)


def test_lookahead_trains():
    lin = paddle.nn.Linear(4, 1)
    inner = paddle.optimizer.Adam(learning_rate=0.02,
                                  parameters=lin.parameters())
    la = paddle.incubate.LookAhead(inner, alpha=0.5, k=2)
    first = last = None
    for _ in range(6):
        loss = ((lin(X) - 1.0) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()
        first = first if first is not None else float(loss.numpy())
        last = float(loss.numpy())
    assert last < first


def test_model_average_apply_restore():
    lin = paddle.nn.Linear(4, 1)
    ma = paddle.incubate.ModelAverage(parameters=lin.parameters())
    w0 = lin.weight.numpy().copy()
    ma.step()
    lin.weight._replace_data(lin.weight._data * 2)
    ma.step()
    ma.apply()
    avg = lin.weight.numpy().copy()
    np.testing.assert_allclose(avg, 1.5 * w0, rtol=1e-6)
    ma.restore()
    np.testing.assert_allclose(lin.weight.numpy(), 2 * w0, rtol=1e-6)


def test_fused_layers_forward():
    fl = paddle.incubate.nn.FusedLinear(4, 3)
    assert fl(X).shape == [8, 3]
    src = rng.standard_normal((2, 5, 8)).astype(np.float32)
    enc = paddle.incubate.nn.FusedTransformerEncoderLayer(
        8, 2, 16, dropout_rate=0.0)
    assert enc(src).shape == [2, 5, 8]
    mt = paddle.incubate.nn.FusedMultiTransformer(8, 2, 16, num_layers=2)
    mt.eval()
    assert mt(src).shape == [2, 5, 8]
    np.testing.assert_allclose(
        paddle.incubate.nn.functional.fused_matmul_bias(
            np.ones((2, 3), np.float32), np.ones((3, 4), np.float32),
            np.ones(4, np.float32)).numpy(), 4.0)


def test_softmax_mask_fuse_ops():
    a = rng.standard_normal((1, 1, 4, 4)).astype(np.float32)
    sm = paddle.incubate.softmax_mask_fuse_upper_triangle(a).numpy()
    assert np.allclose(np.triu(np.asarray(sm)[0, 0], 1), 0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sm).sum(-1), 1.0, rtol=1e-5)
    m = np.where(np.eye(4, dtype=bool), 0.0, -1e30).astype(np.float32)
    sm2 = paddle.incubate.softmax_mask_fuse(a, m[None, None]).numpy()
    np.testing.assert_allclose(np.asarray(sm2)[0, 0],
                               np.eye(4), atol=1e-6)


def test_graph_ops():
    x = np.eye(3, dtype=np.float32)
    src, dst = np.array([0, 1, 2], np.int64), np.array([1, 2, 0], np.int64)
    out = paddle.incubate.graph_send_recv(x, src, dst).numpy()
    expect = np.zeros_like(x)
    for s, d in zip(src, dst):
        expect[d] += x[s]
    np.testing.assert_allclose(out, expect)

    # CSC graph: 0->1,2  1->2  2->0 (cols = dst)
    row = np.array([2, 0, 0, 1], np.int64)
    colptr = np.array([0, 1, 2, 4], np.int64)
    nbrs, counts = paddle.incubate.graph_sample_neighbors(
        row, colptr, np.array([2], np.int64))
    assert sorted(nbrs.numpy().tolist()) == [0, 1]
    assert counts.numpy().tolist() == [2]

    rsrc, rdst, keys = paddle.incubate.graph_reindex(
        np.array([5, 9], np.int64), np.array([9, 7, 5], np.int64),
        np.array([2, 1], np.int64))
    assert keys.numpy().tolist() == [5, 9, 7]
    assert rdst.numpy().tolist() == [0, 0, 1]


def test_varlen_memory_efficient_attention():
    q = rng.standard_normal((2, 2, 6, 8)).astype(np.float32)
    out = paddle.incubate.nn.functional.\
        variable_length_memory_efficient_attention(
            q, q, q, np.array([4, 6], np.int32), np.array([4, 6], np.int32))
    assert out.shape == [2, 2, 6, 8]
    o = out.numpy()
    np.testing.assert_allclose(o[0, :, 4:], 0.0)  # padding rows stay zero


def test_jacobian_hessian_objects():
    jac = paddle.incubate.autograd.Jacobian(
        lambda a: (a ** 2).sum(), np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(np.asarray(jac.numpy()), [2.0, 4.0])
    hes = paddle.incubate.autograd.Hessian(
        lambda a: (a ** 2).sum(), np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(np.asarray(hes.numpy()), 2 * np.eye(2))
    g = paddle.incubate.autograd.forward_grad(
        lambda a: a * 3.0, np.array([1.0], np.float32))
    np.testing.assert_allclose(np.asarray(g.numpy()
                                          if hasattr(g, "numpy") else g), 3.0)


def test_incubate_namespaces_closed():
    import re

    for sub in ["", "/nn", "/nn/functional", "/autograd"]:
        path = f"/root/reference/python/paddle/incubate{sub}/__init__.py"
        ref = set(re.findall(r"'(\w+)'", open(path).read()))
        mod = paddle.incubate
        for part in sub.strip("/").split("/"):
            if part:
                mod = getattr(mod, part)
        missing = sorted(n for n in ref
                         if not hasattr(mod, n) and not n.startswith("_"))
        assert missing == [], f"incubate{sub}: {missing}"


def test_asp_prune_and_sparsity_guarantee():
    """2:4 structured sparsity (reference python/paddle/incubate/asp):
    prune_model halves density with the n:m invariant, and a decorated
    optimizer keeps pruned coordinates at zero across real train steps."""
    import numpy as np

    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.incubate import asp

    rng = np.random.default_rng(0)
    lin = paddle.nn.Linear(8, 8)
    lin.weight.set_value(rng.standard_normal((8, 8)).astype(np.float32))
    opt = asp.decorate(paddle.optimizer.SGD(learning_rate=0.1,
                                            parameters=lin.parameters()))
    asp.prune_model(lin)
    w = lin.weight.numpy()
    assert abs(asp.calculate_density(w) - 0.5) < 1e-6
    assert asp.check_mask_1d(w)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    for _ in range(3):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w2 = lin.weight.numpy()
    assert asp.check_mask_1d(w2), "mask not preserved through steps"
    assert abs(asp.calculate_density(w2) - 0.5) < 0.01
    assert not np.allclose(w, w2)      # training actually moved the weights

    # conv weights (out, in, kh, kw): n:m over the flattened trailing dims
    conv = paddle.nn.Conv2D(4, 8, 3)
    conv.weight.set_value(rng.standard_normal((8, 4, 3, 3)).astype(np.float32))
    asp.prune_model(conv)
    cw = conv.weight.numpy()
    assert abs(asp.calculate_density(cw) - 0.5) < 0.03, asp.calculate_density(cw)
    assert asp.check_mask_1d(cw.reshape(8, -1))
