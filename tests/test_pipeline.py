"""Pipeline parallel: PipelineLayer API + SPMD shard_map pipeline."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.nn import functional as F
from paddlepaddle_tpu.parallel.pipeline import (
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SegmentLayers,
)


def test_segment_uniform():
    assert SegmentLayers.uniform(10, 4) == [0, 3, 6, 8, 10]
    assert SegmentLayers.uniform(8, 4) == [0, 2, 4, 6, 8]


def test_pipeline_layer_build_and_stages():
    descs = [LayerDesc(paddle.nn.Linear, 8, 8) for _ in range(6)]
    pl = PipelineLayer(descs, num_stages=3,
                       loss_fn=lambda out, lb: F.mse_loss(out, lb))
    assert pl.get_num_stages() == 3
    assert pl.segment_parts == [0, 2, 4, 6]
    assert pl.stage_of_layer(0) == 0 and pl.stage_of_layer(5) == 2
    x = np.random.default_rng(0).standard_normal((2, 8)).astype(np.float32)
    out = pl(x)
    assert out.shape == [2, 8]


def test_pipeline_train_batch_matches_single_batch():
    """Microbatched accumulation == full-batch grads (mean losses)."""
    paddle.seed(7)
    descs = [LayerDesc(paddle.nn.Linear, 4, 4) for _ in range(4)]
    pl = PipelineLayer(descs, num_stages=2,
                       loss_fn=lambda out, lb: F.mse_loss(out, lb))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=pl.parameters())
    pp = PipelineParallel(pl, accumulate_steps=4)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((8, 4)).astype(np.float32)
    l0 = float(pp.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt).numpy())
    l1 = float(pp.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt).numpy())
    assert l1 < l0


def test_spmd_pipeline_matches_sequential():
    """shard_map pipeline over pp axis == running the stages sequentially."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddlepaddle_tpu.parallel.pipeline_spmd import spmd_pipeline, stack_stage_params

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    S, M, mb, h = 4, 4, 2, 8
    rng = np.random.default_rng(0)
    per_stage = [{"w": jnp.asarray(rng.standard_normal((h, h)), jnp.float32) / np.sqrt(h)}
                 for _ in range(S)]
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(rng.standard_normal((M * mb, 16, h)), jnp.float32)

    def block(p, a):
        return jnp.tanh(a @ p["w"])

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "pp"))
    out = spmd_pipeline(stacked, x, block, mesh, n_microbatches=M,
                        pp_axis="pp", data_axis="dp")

    ref = x
    for p in per_stage:
        ref = jnp.tanh(ref @ p["w"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_differentiable():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddlepaddle_tpu.parallel.pipeline_spmd import spmd_pipeline, stack_stage_params

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    S, M, mb, h = 2, 2, 2, 4
    rng = np.random.default_rng(1)
    per_stage = [{"w": jnp.asarray(rng.standard_normal((h, h)), jnp.float32)}
                 for _ in range(S)]
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(rng.standard_normal((M * mb, h)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "pp"))

    def block(p, a):
        return jnp.tanh(a @ p["w"])

    def loss(params):
        out = spmd_pipeline(params, x, block, mesh, n_microbatches=M,
                            pp_axis="pp", data_axis="dp")
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(stacked)

    # reference grads through sequential stages
    def ref_loss(params_list):
        a = x
        for p in params_list:
            a = jnp.tanh(a @ p["w"])
        return jnp.sum(a ** 2)

    g_ref = jax.grad(ref_loss)(per_stage)
    for s in range(S):
        np.testing.assert_allclose(np.asarray(g["w"][s]), np.asarray(g_ref[s]["w"]),
                                   rtol=2e-4, atol=2e-5)


def test_spmd_pipeline_interleaved_matches_sequential():
    """VPP (V chunks per device) == running all V*S stages sequentially."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddlepaddle_tpu.parallel.pipeline_spmd import (
        spmd_pipeline_interleaved,
        stack_virtual_stage_params,
    )

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    S, V, M, mb, h = 4, 2, 4, 2, 8
    rng = np.random.default_rng(0)
    per_stage = [{"w": jnp.asarray(rng.standard_normal((h, h)), jnp.float32) / np.sqrt(h)}
                 for _ in range(S * V)]
    stacked = stack_virtual_stage_params(per_stage, S)
    x = jnp.asarray(rng.standard_normal((M * mb, h)), jnp.float32)

    def block(p, a):
        return jnp.tanh(a @ p["w"])

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "pp"))
    out = spmd_pipeline_interleaved(stacked, x, block, mesh, n_microbatches=M,
                                    pp_axis="pp", data_axis="dp")
    ref = x
    for p in per_stage:
        ref = jnp.tanh(ref @ p["w"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # differentiable end to end
    def loss(params):
        o = spmd_pipeline_interleaved(params, x, block, mesh, n_microbatches=M,
                                      pp_axis="pp", data_axis="dp")
        return jnp.sum(o ** 2)

    g = jax.grad(loss)(stacked)
    assert np.isfinite(np.asarray(g["w"]).sum())
