"""Serving robustness: admission control, deadlines & cancellation, circuit
breaker, graceful drain, health probes (inference/robustness.py + serving.py
+ c_api_server.py).

Reference surface: the bounded predictor-pool deployment layer
(paddle/fluid/inference/api/paddle_inference_api.h:229) — callers never see
an unbounded queue and a sick predictor is contained; the load-shedding /
deadline-propagation playbook is "The Tail at Scale" (Dean & Barroso).

Most tests drive the STATIC scheduler with an instant fake model so the
protection layer is exercised without JAX compiles; the continuous-engine
tests (cancel-frees-slot, chaos breaker drill) use the real tiny Llama.
"""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddlepaddle_tpu.inference import (
    CircuitOpenError,
    DeadlineExceededError,
    EngineDrainingError,
    RequestCancelledError,
    RequestValidationError,
    ServerOverloadedError,
    ServingEngine,
)
from paddlepaddle_tpu.inference.robustness import (
    CircuitBreaker,
    QueueWaitEstimator,
)
from paddlepaddle_tpu.inference.serving import GenerationRequest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _await_breaker_closed(eng, timeout=5.0):
    """The engine loop records breaker success AFTER the request future is
    delivered, so sampling .state right after result() races the loop
    thread by a few microseconds — poll with a deadline instead."""
    deadline = time.time() + timeout
    while time.time() < deadline and eng._breaker.state != "closed":
        time.sleep(0.02)
    return eng._breaker.state


class _Out:
    def __init__(self, a):
        self._a = a

    def numpy(self):
        return self._a


class FakeModel:
    """generate_cached lookalike: echoes the prompt + zeros, with injectable
    latency and failures — the serving layer can't tell it from a model."""

    def __init__(self, delay_s=0.0, fail_next=0):
        self.delay_s = delay_s
        self.fail_next = fail_next
        self.calls = 0
        self.batch_sizes = []

    def generate_cached(self, ids, max_new_tokens, temperature=0.0, top_k=0,
                        eos_token_id=None):
        self.calls += 1
        self.batch_sizes.append(ids.shape[0])
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("synthetic decode failure")
        if self.delay_s:
            time.sleep(self.delay_s)
        return _Out(np.concatenate(
            [ids, np.zeros((ids.shape[0], max_new_tokens), np.int32)],
            axis=1))


def _prompt(n=4, v=0):
    return np.full((n,), v, np.int32)


def _static_engine(model=None, **kw):
    kw.setdefault("mode", "static")
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("max_len", 64)
    return ServingEngine(model or FakeModel(), **kw)


# -- admission control -------------------------------------------------------

def test_overload_sheds_typed_and_accepted_complete():
    eng = _static_engine(FakeModel(delay_s=0.05), max_batch_size=1,
                         max_queue=2)
    futs, sheds = [], []
    try:
        for _ in range(12):
            try:
                futs.append(eng.submit(_prompt(), max_new_tokens=2))
            except ServerOverloadedError as e:
                sheds.append(e)
        assert sheds, "burst past max_queue must shed"
        for e in sheds:
            assert e.queue_depth >= 2
            assert e.retry_after_s >= 0.0
        for f in futs:        # every accepted request completes
            assert f.result(30).shape == (6,)
        assert eng.stats["shed"] == len(sheds)
        assert eng.health()["queue_depth"] == 0
    finally:
        eng.stop()


def test_off_sentinels_disable_limits():
    """0 / 0.0 mean OFF from the constructor exactly like from the flags:
    max_queue=0 is unbounded (the seed behavior), not shed-everything."""
    eng = _static_engine(max_queue=0, max_queue_wait_s=0.0,
                         default_deadline_s=0.0, decode_timeout_s=0.0)
    try:
        assert eng.max_queue is None
        assert eng.max_queue_wait_s is None
        assert eng.default_deadline_s is None
        assert eng.decode_timeout_s is None
        futs = [eng.submit(_prompt(), max_new_tokens=2) for _ in range(16)]
        for f in futs:
            f.result(30)       # nothing shed, no deadline, no watchdog
        assert eng.stats["shed"] == 0
        assert eng._watchdog_thread is None
    finally:
        eng.stop()


def test_queue_wait_estimate_sheds():
    eng = _static_engine(FakeModel(delay_s=0.1), max_batch_size=1,
                         max_queue_wait_s=0.15)
    try:
        first = eng.submit(_prompt(), max_new_tokens=2)
        first.result(10)      # seeds the EWMA with ~0.1s per attempt
        futs = [eng.submit(_prompt(), max_new_tokens=2)]  # depth 0: admitted
        with pytest.raises(ServerOverloadedError, match="estimated"):
            for _ in range(20):   # estimated wait grows with depth
                futs.append(eng.submit(_prompt(), max_new_tokens=2))
        for f in futs:
            f.result(30)
    finally:
        eng.stop()


def test_validation_rejects_at_submit():
    eng = _static_engine(max_len=16)
    try:
        with pytest.raises(RequestValidationError, match="max_len"):
            eng.submit(_prompt(14), max_new_tokens=8)
        with pytest.raises(ValueError):   # subclass contract for old callers
            eng.submit(_prompt(14), max_new_tokens=8)
        with pytest.raises(RequestValidationError, match="max_new_tokens"):
            eng.submit(_prompt(), max_new_tokens=0)
        assert eng.stats["requests"] == 0   # nothing was queued
    finally:
        eng.stop()


# -- deadlines & cancellation ------------------------------------------------

def test_deadline_expired_sheds_before_admission():
    eng = _static_engine()
    try:
        with pytest.raises(DeadlineExceededError):
            eng.submit(_prompt(), max_new_tokens=2, deadline_s=0.0)
        assert eng.stats["deadline_expired"] == 1
    finally:
        eng.stop()


def test_deadline_expired_in_queue_is_shed_not_decoded():
    model = FakeModel(delay_s=0.15)
    eng = _static_engine(model, max_batch_size=1)
    try:
        head = eng.submit(_prompt(), max_new_tokens=2)        # occupies engine
        doomed = eng.submit(_prompt(6), max_new_tokens=2, deadline_s=0.01)
        with pytest.raises(DeadlineExceededError):
            doomed.result(10)
        head.result(10)
        # the expired request never reached the model (prompt length 6
        # would have been its own batch)
        assert all(b == 1 for b in model.batch_sizes)
        assert model.calls == 1
    finally:
        eng.stop()


def test_cancel_queued_request():
    eng = _static_engine(FakeModel(delay_s=0.1), max_batch_size=1)
    try:
        head = eng.submit(_prompt(), max_new_tokens=2)
        queued = eng.submit(_prompt(6), max_new_tokens=2)
        assert queued.cancel() is True
        assert queued.cancel() is False       # already finished
        with pytest.raises(RequestCancelledError):
            queued.result(5)
        head.result(10)
    finally:
        eng.stop()


# -- circuit breaker ---------------------------------------------------------

def test_breaker_unit_cycle():
    transitions = []
    b = CircuitBreaker(threshold=2, reset_s=0.1,
                       on_transition=lambda o, n: transitions.append((o, n)))
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open" and not b.allow()
    assert b.retry_after_s() > 0
    time.sleep(0.12)
    assert b.allow() and b.state == "half_open"   # probe window
    b.record_failure()                            # probe failed
    assert b.state == "open"
    time.sleep(0.12)
    assert b.allow()
    b.record_success()                            # probe succeeded
    assert b.state == "closed" and b.consecutive_failures == 0
    assert ("closed", "open") in transitions
    assert ("half_open", "closed") in transitions


def test_breaker_opens_then_recovers_static():
    model = FakeModel(fail_next=3)
    eng = _static_engine(model, max_batch_size=1, breaker_threshold=3,
                         breaker_reset_s=0.2)
    try:
        for _ in range(3):
            f = eng.submit(_prompt(), max_new_tokens=2)
            with pytest.raises(RuntimeError, match="synthetic"):
                f.result(10)
        # breaker is open: fail-fast submits with a retry hint
        deadline = time.time() + 2
        saw_open = False
        while time.time() < deadline:
            try:
                f = eng.submit(_prompt(), max_new_tokens=2)
                break
            except CircuitOpenError as e:
                saw_open = True
                assert e.retry_after_s <= 0.2 + 0.05
                time.sleep(0.02)
        else:
            pytest.fail("breaker never let the probe through")
        assert saw_open
        f.result(10)       # half-open probe succeeded (failures exhausted)
        assert _await_breaker_closed(eng) == "closed"
        assert eng.health()["ok"]
        assert eng.stats["decode_failures"] == 3
        assert eng.stats["batches_failed"] == 3
    finally:
        eng.stop()


def test_hung_decode_watchdog_trips_breaker():
    model = FakeModel(delay_s=0.5)
    eng = _static_engine(model, max_batch_size=1, breaker_threshold=100,
                         breaker_reset_s=10.0, decode_timeout_s=0.05)
    try:
        slow = eng.submit(_prompt(), max_new_tokens=2)
        time.sleep(0.2)     # watchdog interval + timeout elapse mid-decode
        assert eng._breaker.state == "open"     # tripped while hung
        assert not eng.health()["ok"]
        with pytest.raises(CircuitOpenError):
            eng.submit(_prompt(), max_new_tokens=2)
        slow.result(10)     # the hung decode eventually returned fine...
        time.sleep(0.05)
        assert eng._breaker.state == "closed"   # ...which closes the breaker
    finally:
        eng.stop()


# -- graceful drain ----------------------------------------------------------

def test_drain_finishes_inflight_and_sheds_rest():
    eng = _static_engine(FakeModel(delay_s=0.1), max_batch_size=1)
    try:
        futs = [eng.submit(_prompt(), max_new_tokens=2) for _ in range(5)]
        res = eng.drain(timeout=0.25)
        assert all(f.done() for f in futs)
        served = shed = 0
        for f in futs:
            try:
                f.result(0)
                served += 1
            except EngineDrainingError:
                shed += 1
        assert served >= 1           # in-flight work finished
        assert shed == res["shed"] and shed >= 1
        with pytest.raises(EngineDrainingError):
            eng.submit(_prompt(), max_new_tokens=2)
        assert eng.health()["state"] == "stopped"
    finally:
        eng.stop()


def test_restart_after_drain_reopens_admission():
    """Regression: drain() used to leave the engine permanently refusing
    admission — start() after a COMPLETED drain must re-open it (drain
    state cleared, breaker/watchdog re-armed). Rolling restart
    (inference/router.py) is built on this sequence."""
    eng = _static_engine(breaker_threshold=2)
    try:
        eng.submit(_prompt(), max_new_tokens=2).result(10)
        eng._breaker.trip()                     # sick engine going down...
        res = eng.drain(timeout=5)
        assert res["clean"]
        with pytest.raises(EngineDrainingError):
            eng.submit(_prompt(), max_new_tokens=2)
        eng.start()                             # ...comes back clean
        assert eng._breaker.state == "closed"   # old epoch's history gone
        out = eng.submit(_prompt(), max_new_tokens=2).result(10)
        assert out.shape == (6,)
        h = eng.health()
        assert h["state"] == "serving" and h["ok"]
        # drain -> start -> drain again still works (the router does this
        # on every rolling restart)
        assert eng.drain(timeout=5)["clean"]
        eng.start()
        eng.submit(_prompt(), max_new_tokens=2).result(10)
    finally:
        eng.stop()


def test_drain_idempotent_and_clean_when_idle():
    eng = _static_engine()
    eng.submit(_prompt(), max_new_tokens=2).result(10)
    res = eng.drain(timeout=5)
    assert res["clean"] and res["shed"] == 0
    assert eng.drain(timeout=1)["shed"] == 0      # second drain is a no-op


def test_sigterm_drains_before_exit_143(tmp_path):
    """Acceptance: a SIGTERM'd serving host drains in-flight requests via
    resilience.preemption and exits with the restart-eligible 143."""
    sentinel = tmp_path / "drained.json"
    script = tmp_path / "serve_and_term.py"
    script.write_text(f"""
import json, os, signal, sys, time
import numpy as np
sys.path.insert(0, {_REPO!r})
sys.path.insert(0, {os.path.join(_REPO, 'tests')!r})
from test_serving_robustness import FakeModel, _static_engine
from paddlepaddle_tpu.resilience.preemption import install_preemption_handler

eng = _static_engine(FakeModel(delay_s=0.05), max_batch_size=1)
eng.install_preemption_hook(timeout=5.0)
# second callback runs AFTER the drain: snapshot what the drain left behind
results = {{}}
futs = [eng.submit(np.full((4,), 0, np.int32), max_new_tokens=2)
        for _ in range(3)]
def snapshot():
    h = eng.health()
    results["state"] = h["state"]
    results["done"] = all(f.done() for f in futs)
    open({str(sentinel)!r}, "w").write(json.dumps(results))
install_preemption_handler(snapshot)
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(30)   # never reached: the handler exits 143
""")
    proc = subprocess.run([sys.executable, str(script)], timeout=60,
                          capture_output=True, text=True,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 143, (proc.returncode, proc.stderr[-2000:])
    data = json.loads(sentinel.read_text())
    assert data["done"] is True          # nothing left hanging
    assert data["state"] == "stopped"


# -- scheduler fairness (deferred FIFO) --------------------------------------

def test_incompatible_request_not_starved():
    """Seed bug: an incompatible leftover was re-queued behind newer
    arrivals every cycle. Now it parks in a FIFO deferred list drained
    ahead of the queue — it becomes the NEXT batch's leader."""
    eng = _static_engine(max_wait_ms=20.0)
    # fill the queue before the loop starts: no thread, direct puts
    reqs = [GenerationRequest(_prompt(4), 2, 0.0, 0, None),
            GenerationRequest(_prompt(8), 2, 0.0, 0, None),   # incompatible
            GenerationRequest(_prompt(4), 2, 0.0, 0, None),
            GenerationRequest(_prompt(4), 2, 0.0, 0, None)]
    for r in reqs:
        eng._queue.put(r)
    b1 = eng._collect_batch()
    assert [r.prompt_ids.shape[1] for r in b1] == [4, 4, 4]
    b2 = eng._collect_batch()          # the deferred 8-prompt leads NOW,
    assert [r.prompt_ids.shape[1] for r in b2] == [8]   # not behind arrivals
    # sustained compatible load cannot push a deferred request back
    eng._queue.put(GenerationRequest(_prompt(8), 2, 0.0, 0, None))
    eng._queue.put(GenerationRequest(_prompt(4), 2, 0.0, 0, None))
    b3 = eng._collect_batch()
    lead = b3[0].prompt_ids.shape[1]
    b4 = eng._collect_batch()
    assert {lead, b4[0].prompt_ids.shape[1]} == {4, 8}


# -- static-mode outcome accounting ------------------------------------------

def test_static_batch_outcome_accounting():
    import paddlepaddle_tpu.observability as obs

    model = FakeModel(fail_next=1)
    eng = _static_engine(model, max_batch_size=1, breaker_threshold=10)
    obs.enable(trace=False, metrics=True, watchdog_=False)
    try:
        bad = eng.submit(_prompt(), max_new_tokens=2)
        with pytest.raises(RuntimeError):
            bad.result(10)
        good = eng.submit(_prompt(), max_new_tokens=2)
        good.result(10)
        # a failed batch is NOT counted as served
        assert eng.stats["batches"] == 1
        assert eng.stats["batches_failed"] == 1
        snap = obs.snapshot()
        batches = snap.get("paddle_serving_batches_total", {})
        assert batches.get((("outcome", "error"),)) == 1
        assert batches.get((("outcome", "ok"),)) == 1
    finally:
        obs.disable()
        obs.reset()
        eng.stop()


# -- health probe over the C protocol ----------------------------------------

class _DummyPredictor:
    def get_input_names(self):
        return ["input_0"]

    def get_output_names(self):
        return ["output_0"]

    def run(self, inputs):
        return [np.asarray(inputs[0], np.float32)]


def _send_frame(path, payload):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(path)
    s.sendall(struct.pack("<Q", len(payload)) + payload)
    head = b""
    while len(head) < 8:
        chunk = s.recv(8 - len(head))
        if not chunk:
            s.close()
            return None, None
        head += chunk
    (n,) = struct.unpack("<Q", head)
    body = b""
    while len(body) < n:
        body += s.recv(n - len(body))
    return s, body


def test_capi_health_frame_and_malformed_frames(tmp_path):
    from paddlepaddle_tpu.inference.c_api_server import (
        _MAGIC, _OP_HEALTH, CApiServer)

    eng = _static_engine()
    path = str(tmp_path / "pd.sock")
    srv = CApiServer(_DummyPredictor(), path, health_fn=eng.health)
    srv.start()
    try:
        # health frame: JSON readiness snapshot
        s, body = _send_frame(path, struct.pack("<IB", _MAGIC, _OP_HEALTH))
        magic, status = struct.unpack_from("<IB", body)
        assert magic == _MAGIC and status == 0
        (ln,) = struct.unpack_from("<I", body, 5)
        snap = json.loads(body[9:9 + ln].decode())
        assert snap["mode"] == "static"
        assert {"state", "ok", "queue_depth", "breaker"} <= set(snap)
        s.close()

        # bad magic: error frame, then the server closes the connection
        s, body = _send_frame(path, struct.pack("<IB", 0xDEAD, 7))
        assert struct.unpack_from("<IB", body)[1] == 1
        s.settimeout(5)
        assert s.recv(1) == b""       # closed by server
        s.close()

        # truncated frame (shorter than the header): typed error, no crash
        s, body = _send_frame(path, b"\x01\x02")
        assert struct.unpack_from("<IB", body)[1] == 1
        assert b"malformed" in body
        s.close()

        # truncated tensor payload inside a RUN op
        garbage = struct.pack("<IB", _MAGIC, 1) + struct.pack("<I", 3)
        s, body = _send_frame(path, garbage)
        assert struct.unpack_from("<IB", body)[1] == 1
        s.close()

        # absurd length prefix: error frame instead of buffering 2^60 bytes
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(struct.pack("<Q", 1 << 60))
        head = s.recv(8)
        (n,) = struct.unpack("<Q", head)
        body = b""
        while len(body) < n:
            body += s.recv(n - len(body))
        assert struct.unpack_from("<IB", body)[1] == 1
        assert b"exceeds max" in body
        s.close()

        # the server survived all of it: a well-formed RUN still works
        x = np.arange(4, dtype=np.float32)
        t = (struct.pack("<I", 7) + b"input_0" + struct.pack("<B", 0)
             + struct.pack("<I", 1) + struct.pack("<q", 4) + x.tobytes())
        frame = struct.pack("<IB", _MAGIC, 1) + struct.pack("<I", 1) + t
        s, body = _send_frame(path, frame)
        assert struct.unpack_from("<IB", body)[1] == 0
        s.close()
        # closed connections get pruned by their handler thread's EOF
        # observation — give the threads a moment under box load
        deadline = time.time() + 5.0
        while len(srv._conns) > 1 and time.time() < deadline:
            time.sleep(0.01)
        assert len(srv._conns) <= 1
    finally:
        srv.stop()
        eng.stop()


# -- chaos drills ------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_admit_seam_fires():
    from paddlepaddle_tpu.resilience import chaos

    chaos.configure("serving.admit:exc:@1",
                    seed=int(os.environ.get("PADDLE_CHAOS_SEED", "1234")))
    eng = _static_engine()
    try:
        with pytest.raises(chaos.ChaosError):
            eng.submit(_prompt(), max_new_tokens=2)
        eng.submit(_prompt(), max_new_tokens=2).result(10)  # next one fine
        assert chaos.fire_counts().get("serving.admit") == 1
    finally:
        chaos.disable()
        eng.stop()


@pytest.mark.chaos
def test_chaos_decode_storm_opens_breaker_then_recovers():
    """Acceptance drill (static scheduler, instant model): an injected
    serving.decode fault storm opens the breaker; the engine recovers to
    serving WITHOUT a restart once the half-open probe passes."""
    from paddlepaddle_tpu.resilience import chaos

    chaos.configure("serving.decode:exc:x3",
                    seed=int(os.environ.get("PADDLE_CHAOS_SEED", "1234")))
    eng = _static_engine(max_batch_size=1, breaker_threshold=3,
                         breaker_reset_s=0.2)
    try:
        for _ in range(3):
            f = eng.submit(_prompt(), max_new_tokens=2)
            with pytest.raises(chaos.ChaosError):
                f.result(10)
        assert eng._breaker.state == "open"
        with pytest.raises(CircuitOpenError):
            eng.submit(_prompt(), max_new_tokens=2)
        time.sleep(0.25)                  # storm exhausted + reset window
        eng.submit(_prompt(), max_new_tokens=2).result(10)
        assert _await_breaker_closed(eng) == "closed"
        assert chaos.fire_counts()["serving.decode"] == 3
        assert eng.health()["ok"]
    finally:
        chaos.disable()
        eng.stop()


# -- continuous engine (real model) ------------------------------------------

def _llama():
    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(
        vocab_size=64, hidden_size=32, layers=2, heads=4, kv_heads=2,
        max_len=96))


def test_continuous_cancel_frees_slot_mid_decode():
    m = _llama()
    rng = np.random.default_rng(0)
    p = rng.integers(0, 64, (8,)).astype(np.int32)
    with ServingEngine(m, max_batch_size=2, decode_chunk=4) as eng:
        eng.submit(p, max_new_tokens=4).result(300)      # warm the compiles
        doomed = eng.submit(rng.integers(0, 64, (8,)).astype(np.int32),
                            max_new_tokens=80)
        assert doomed.cancel() is True
        with pytest.raises(RequestCancelledError):
            doomed.result(30)
        # the slot is released and reusable: another request completes and
        # no phantom lane stays busy
        out = eng.submit(p, max_new_tokens=4).result(120)
        assert out.shape[0] == 12
        deadline = time.time() + 10
        while time.time() < deadline and eng._engine.busy_slots():
            time.sleep(0.05)
        assert eng._engine.busy_slots() == 0
        assert eng.stats["cancelled"] >= 1


@pytest.mark.chaos
def test_chaos_continuous_breaker_recovery():
    """The same storm through the CONTINUOUS engine: failed chunks fail the
    slots, open the breaker, and the engine serves again after recovery —
    deterministic under PADDLE_CHAOS_SEED."""
    from paddlepaddle_tpu.resilience import chaos

    m = _llama()
    rng = np.random.default_rng(1)
    p = rng.integers(0, 64, (8,)).astype(np.int32)
    # ONE slot: each injected failure is its own decode attempt, so the
    # storm deterministically reaches the breaker threshold
    eng = ServingEngine(m, max_batch_size=1, decode_chunk=4,
                        breaker_threshold=2, breaker_reset_s=0.2)
    # observe transitions via the synchronous callback — sampling .state
    # from the test thread can miss the short-lived "open" phase entirely
    transitions = []
    orig = eng._breaker._on_transition
    eng._breaker._on_transition = \
        lambda o, n: (transitions.append((o, n)), orig(o, n))
    try:
        eng.submit(p, max_new_tokens=4).result(300)      # warm the compiles
        chaos.configure("serving.decode:exc:x2",
                        seed=int(os.environ.get("PADDLE_CHAOS_SEED", "1234")))
        failed = [eng.submit(rng.integers(0, 64, (8,)).astype(np.int32),
                             max_new_tokens=4) for _ in range(2)]
        for f in failed:
            with pytest.raises(chaos.ChaosError):
                f.result(120)
        deadline = time.time() + 10
        while time.time() < deadline \
                and ("closed", "open") not in transitions:
            time.sleep(0.02)
        assert ("closed", "open") in transitions, transitions
        time.sleep(0.25)                  # storm exhausted + reset window
        out = eng.submit(p, max_new_tokens=4).result(120)   # recovered
        assert out.shape[0] == 12
        assert _await_breaker_closed(eng) == "closed"
        assert eng.stats["decode_failures"] >= 2
    finally:
        chaos.disable()
        eng.stop()


# -- soak --------------------------------------------------------------------

@pytest.mark.slow
def test_soak_overload_burst_and_recovery():
    """Acceptance: with max_queue=8, a 64-request burst yields typed sheds
    (never a hang or an unbounded queue), every accepted request completes,
    and the queue-depth gauge returns to 0."""
    import paddlepaddle_tpu.observability as obs

    obs.enable(trace=False, metrics=True, watchdog_=False)
    eng = _static_engine(FakeModel(delay_s=0.01), max_batch_size=4,
                         max_queue=8)
    accepted, sheds, lock = [], [], threading.Lock()
    try:
        def client(i):
            for j in range(8):
                try:
                    f = eng.submit(_prompt(v=i), max_new_tokens=2)
                    with lock:
                        accepted.append(f)
                except ServerOverloadedError as e:
                    assert e.queue_depth >= 8
                    with lock:
                        sheds.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert len(accepted) + len(sheds) == 64
        assert sheds, "a 64-burst into max_queue=8 must shed"
        for f in accepted:
            assert f.result(60).shape == (6,)     # all accepted complete
        time.sleep(0.3)       # idle loop republishes the depth gauge
        snap = obs.snapshot()
        assert snap["paddle_serving_queue_depth"][()] == 0
        shed_counts = snap.get("paddle_serving_shed_total", {})
        total_shed = sum(v for k, v in shed_counts.items()
                         if dict(k).get("reason") == "queue_full")
        assert total_shed == len(sheds)
        assert eng.health()["ok"]
        text = obs.to_prometheus_text()
        assert "paddle_serving_shed_total" in text
    finally:
        obs.disable()
        obs.reset()
        eng.stop()


def test_queue_wait_estimator_unit():
    est = QueueWaitEstimator(alpha=0.5)
    assert est.estimate_wait_s(100, 4) == 0.0     # never sheds blind
    est.observe(1.0)
    assert est.estimate_wait_s(0, 4) == 0.0       # nothing ahead of it
    assert est.estimate_wait_s(8, 4) == pytest.approx(2.0)
    est.observe(0.0)
    assert est.ewma_s == pytest.approx(0.5)
