"""True multi-process fleet (inference/remote_replica.py): the
socket-backed RemoteReplicaClient against a real replica_main process,
the ReplicaSupervisor's crash-loop handling, and router failover over
real process death.

Budget discipline: every fast test shares ONE module-scoped replica
process (tiny preset, warmup off — a spawn is ~2.5 s and we pay it
once); tests that must kill or crash-loop a process spawn their own.
The full 2-process rollout + SIGKILL drill is `chaos`-marked and runs
via tools/run_chaos.sh, not tier-1.
"""

import json
import struct
import threading
import time

import numpy as np
import pytest

from paddlepaddle_tpu.inference.c_api_server import (
    _MAGIC,
    _OP_SUBMIT,
    _ST_CHUNK,
    _pack_tensor,
)
from paddlepaddle_tpu.inference.remote_replica import (
    RemoteReplicaClient,
    ReplicaSupervisor,
    _parse_reply,
    _recv_frame,
    _send_frame,
)
from paddlepaddle_tpu.inference.robustness import (
    CircuitOpenError,
    DeployError,
    FleetUnavailableError,
    KVCapacityError,
    RequestValidationError,
    ServerOverloadedError,
    ServingError,
    error_from_wire,
    error_to_wire,
)
from paddlepaddle_tpu.observability import reqtrace
from paddlepaddle_tpu.resilience.retry import RetryPolicy


@pytest.fixture(scope="module")
def replica():
    """ONE shared replica process for every fast test in this module."""
    sup = ReplicaSupervisor(preset="tiny", name="t0", warmup="off",
                            ready_timeout_s=120.0)
    cli = RemoteReplicaClient(supervisor=sup, name="t0")
    cli.start()
    yield cli
    sup.stop(drain_timeout=2.0)


# -- wire-format units (no process) ------------------------------------------

def test_error_wire_roundtrip_preserves_type_and_fields():
    cases = [
        ServerOverloadedError("full", queue_depth=7, retry_after_s=0.25),
        CircuitOpenError("open", retry_after_s=1.5),
        KVCapacityError("too big", pages_needed=9, pages_capacity=4),
        FleetUnavailableError("none", replicas=3, healthy=0,
                              retry_after_s=0.5),
        DeployError("gate", stage="canary", reasons=["ttft"]),
        RequestValidationError("bad prompt"),
    ]
    for exc in cases:
        back = error_from_wire(json.loads(json.dumps(error_to_wire(exc))))
        assert type(back) is type(exc), (exc, back)
        assert str(exc) in str(back)
    over = error_from_wire(error_to_wire(cases[0]))
    assert over.queue_depth == 7 and over.retry_after_s == 0.25
    kv = error_from_wire(error_to_wire(cases[2]))
    assert kv.pages_needed == 9 and kv.pages_capacity == 4


def test_error_wire_unknown_types_become_retryable_runtime_errors():
    from paddlepaddle_tpu.inference.router import _retryable

    exc = error_from_wire({"type": "SomethingExotic", "msg": "boom"})
    assert isinstance(exc, RuntimeError)
    assert not isinstance(exc, ServingError)
    assert _retryable(exc)         # untyped remote failure → failover
    t = error_from_wire({"type": "TimeoutError", "msg": "late"})
    assert isinstance(t, TimeoutError)
    # a hostile/garbage doc still yields an exception, never a crash
    assert isinstance(error_from_wire({}), RuntimeError)


# -- live replica: submit parity ---------------------------------------------

def test_remote_submit_roundtrip_with_slo_stamps(replica):
    fut = replica.submit(np.arange(1, 9, dtype=np.int32),
                         max_new_tokens=6)
    out = fut.result(120)
    assert out.shape == (14,)                 # 8 prompt + 6 new
    assert np.array_equal(out[:8], np.arange(1, 9))
    slo = fut.slo()
    # the same stamp set the in-process engine produces, client-clocked
    assert slo["new_tokens"] == 6
    assert slo["ttft_s"] and slo["ttft_s"] > 0
    assert slo["latency_s"] >= slo["ttft_s"]
    assert slo["tpot_s"] is not None and slo["tpot_s"] >= 0
    assert fut._t_admit is not None and fut._t_first is not None
    assert fut._streaming


def test_remote_typed_admission_error_is_synchronous(replica):
    # over-long prompt: the replica's engine refuses at admission; the
    # client's submit() must RAISE the same typed error in-process
    # submit() would — not hand back a future that fails later
    with pytest.raises(RequestValidationError):
        replica.submit(np.zeros(4096, dtype=np.int32), max_new_tokens=4)
    with pytest.raises(RequestValidationError):
        replica.submit(np.arange(4, dtype=np.int32), max_new_tokens=0)
    # the replica survives refusals: next request serves
    fut = replica.submit(np.arange(4, dtype=np.int32), max_new_tokens=2)
    assert fut.result(60).shape == (6,)


def test_journey_stitches_across_the_process_hop(replica):
    j = reqtrace.Journey("hop-req", 256)
    fut = replica.submit(np.arange(6, dtype=np.int32), max_new_tokens=4,
                         trace=j)
    fut.result(60)
    names = [s.get("name") for s in j.spans]
    assert "engine.submit" in names and "admit" in names, names
    assert "first_token" in names
    # replica-side spans carry the replica tag and client-rebased times
    remote = [s for s in j.spans if s.get("replica") == "t0"]
    assert remote, j.spans
    for s in remote:
        assert s["t"] >= 0


def test_health_carries_supervisor_block(replica):
    h = replica.health()
    assert h.get("ok") is True
    sup = h["supervisor"]
    assert isinstance(sup["pid"], int) and sup["pid"] > 0
    assert sup["state"] == "serving"
    assert sup["spawns"] >= 1 and sup["crashes"] == 0
    assert replica.warmup().get("remote") is True


def test_client_disconnect_mid_stream_releases_the_slot(replica):
    baseline = replica.health().get("pages_free")
    assert baseline is not None
    # raw-socket half of the protocol: submit a long decode, read ONLY
    # the accepted frame, then vanish — the server's disconnect probe
    # must cancel the request and hand its pages back
    hdr = json.dumps({"max_new_tokens": 64}).encode()
    payload = (struct.pack("<IB", _MAGIC, _OP_SUBMIT)
               + struct.pack("<I", len(hdr)) + hdr
               + _pack_tensor("prompt", np.arange(8, dtype=np.int32)))
    s = replica._connect()
    _send_frame(s, payload)
    status, _c = _parse_reply(_recv_frame(s))
    assert status == _ST_CHUNK                # accepted
    s.close()
    deadline = time.monotonic() + 30
    free = None
    while time.monotonic() < deadline:
        free = replica.health().get("pages_free")
        if free == baseline:
            break
        time.sleep(0.1)
    assert free == baseline, (free, baseline)


def test_cancel_propagates_to_the_replica(replica):
    baseline = replica.health().get("pages_free")
    fut = replica.submit(np.arange(8, dtype=np.int32), max_new_tokens=64)
    assert fut.cancel()
    with pytest.raises(Exception):
        fut.result(10)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if replica.health().get("pages_free") == baseline:
            break
        time.sleep(0.1)
    assert replica.health().get("pages_free") == baseline


# -- supervisor lifecycle (own processes) ------------------------------------

def test_crash_loop_backoff_and_last_exit_capture(tmp_path):
    """A bundle that exits at boot (strict --bundle on a path that does
    not exist) crash-loops: spawn, die with code 3, backoff, respawn,
    die, give up at max_respawns — with every step counted and the last
    exit (code + final stderr line) captured for the health block."""
    sup = ReplicaSupervisor(
        bundle=str(tmp_path / "no-such-bundle"), preset="tiny",
        name="crashy", warmup="off", ready_timeout_s=90.0,
        max_respawns=1,
        backoff=RetryPolicy(max_attempts=4, base_delay=0.05,
                            max_delay=0.2, jitter=0.0))
    try:
        with pytest.raises(RuntimeError, match="never became ready"):
            sup.start()
    finally:
        sup.stop()
    assert sup.stats["spawns"] == 2           # original + one respawn
    assert sup.stats["crashes"] == 2
    assert sup.stats["crash_loop_backoffs"] >= 1
    assert sup.last_exit is not None and sup.last_exit["code"] == 3
    assert "bundle" in str(sup.last_exit.get("reason"))
    assert sup.info()["pid"] is None


def test_sigkill_mid_stream_fails_over_and_restart_revives():
    """The chaos seam over a REAL process: SIGKILL mid-decode → every
    in-flight future fails untyped (the router-failover class), the dead
    replica refuses probes, and restart() respawns a serving process."""
    from paddlepaddle_tpu.inference.router import _retryable

    sup = ReplicaSupervisor(preset="tiny", name="victim", warmup="off",
                            ready_timeout_s=120.0)
    cli = RemoteReplicaClient(supervisor=sup, name="victim")
    cli.start()
    try:
        # prime the decode programs so the killed request is mid-stream,
        # not mid-compile
        cli.submit(np.arange(8, dtype=np.int32),
                   max_new_tokens=2).result(120)
        fut = cli.submit(np.arange(8, dtype=np.int32), max_new_tokens=64)
        while fut._t_admit is None and not fut.done():
            time.sleep(0.01)
        cli.kill()
        with pytest.raises(Exception) as ei:
            fut.result(30)
        assert _retryable(ei.value), ei.value   # untyped → failover
        with pytest.raises(ConnectionError):
            cli.health()
        cli.restart()
        assert cli.health()["ok"] is True
        assert cli.generation == 1
        out = cli.submit(np.arange(4, dtype=np.int32),
                         max_new_tokens=2).result(120)
        assert out.shape == (6,)
        assert sup.stats["restarts"] == 1
    finally:
        sup.stop()


# -- the full drill: processes under the router + rollout --------------------

@pytest.mark.chaos
def test_process_fleet_drill_rollout_step_traffic_sigkill(tmp_path):
    """PR 13's chaos drill promoted to real OS processes: a 2-process
    fleet behind the FleetController, a REAL bundle rollout (each
    process respawns onto ``--bundle`` in strict mode — a fallback to
    lazy builds exits 3, so zero silent in-process fallbacks by
    construction), 4× open-loop step traffic throughout, and one replica
    SIGKILL'd mid-rollout. Invariants: zero lost futures, the fleet
    serves real processes afterwards."""
    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.inference.fleet import FleetController, FleetPolicy
    from paddlepaddle_tpu.inference.remote_replica import (
        ProcessReplicaFactory,
    )
    from paddlepaddle_tpu.inference.replica_main import PRESETS
    from paddlepaddle_tpu.inference.serving import ServingEngine
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    # the candidate bundle, saved with replica_main's exact engine
    # geometry (bundle programs are shape-keyed; strict load proves it)
    paddle.seed(0)
    model = LlamaForCausalLM(
        LlamaConfig(dtype="float32", **PRESETS["tiny"]))
    saver = ServingEngine(model, max_batch_size=2, decode_chunk=4,
                          kv_page_size=16)
    saver.warmup()
    bundle = str(tmp_path / "bundle")
    saver.save_serving_bundle(bundle)
    saver.drain(2.0)

    factory = ProcessReplicaFactory(
        preset="tiny", warmup="off",
        supervisor_kw={"ready_timeout_s": 180.0})
    ctl = FleetController(
        factory, initial_replicas=2,
        policy=FleetPolicy(min_replicas=2, max_replicas=2),
        probe_interval_s=0.2, name_prefix="proc")
    ctl.start(autoscaler=False)
    router = ctl.router
    try:
        futs, stop = [], threading.Event()

        def _load():
            while not stop.is_set() and len(futs) < 160:
                for _ in range(4):            # the 4× step
                    try:
                        futs.append(router.submit(
                            np.arange(6, dtype=np.int32),
                            max_new_tokens=4))
                    except ServingError:
                        pass                  # typed shed = accounted
                time.sleep(0.1)

        t = threading.Thread(target=_load, daemon=True)
        t.start()
        time.sleep(0.5)

        # the rollout, concurrent with the step traffic
        dep = {}

        def _deploy():
            try:
                dep["result"] = ctl.deploy(bundle, canary_requests=2,
                                           canary_new_tokens=2)
            except Exception as e:  # noqa: BLE001 — asserted below
                dep["error"] = e

        d = threading.Thread(target=_deploy, daemon=True)
        d.start()
        # once the canary is named, SIGKILL the OTHER replica process —
        # real death in the middle of a live rollout
        deadline = time.monotonic() + 60
        while (ctl.rollout.get("state") == "idle"
               or not ctl.rollout.get("replica")) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        canary = ctl.rollout.get("replica")
        victim = next(r.client for r in router._replicas
                      if r.name != canary)
        assert victim.supervisor.pid() is not None
        victim.kill()
        d.join(300)
        assert "error" not in dep, dep.get("error")
        stop.set()
        t.join(10)

        # anything the rollout did not already revive, the router's
        # recovery path respawns (a real process restart)
        for rep in router._replicas:
            try:
                rep.client.health()
            except Exception:
                router.restart_replica(rep.name)

        resolved = ok = 0
        for f in futs:
            try:
                f.result(120)
                ok += 1
            except Exception:
                pass          # typed shed or untyped infra — accounted
            resolved += 1
        assert resolved == len(futs)          # ZERO lost futures
        assert ok > 0

        # the fleet serves real processes after the drill
        h = router.health()
        assert h["router"]["healthy"] == 2, h
        for rep in h["replicas"].values():
            assert rep["supervisor"]["pid"] is not None
        out = router.submit(np.arange(4, dtype=np.int32),
                            max_new_tokens=2).result(120)
        assert out.shape == (6,)

        res = dep["result"]
        if res.get("ok"):
            # rollout completed: every process serves the candidate
            # bundle, loaded strictly in a fresh interpreter
            assert ctl.version == bundle
            for rep in router._replicas:
                assert rep.client.supervisor.bundle == bundle
                assert rep.client.health()["supervisor"]["pid"]
        else:
            # the kill cost the candidate its gate: rolled back, still
            # serving the previous version — an EXPECTED drill outcome,
            # but it must say so, not hang
            assert res.get("reasons"), res
    finally:
        ctl.stop()
