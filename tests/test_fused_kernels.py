"""Fused Pallas kernels for the two measured data-movement floors
(ops/kernels/gather_gemm.py + ops/kernels/paged_attention.py, ISSUE 15).

The acceptance surface: interpret-mode parity units (gather-GEMM vs the
einsum/sorted dispatch on planted ragged expert loads incl. empty experts
and capacity overflow; the paged-attention kernel vs the reference
``pool[page_table]`` formulation at W=1 and W=k+1), engine-level
TOKEN-EXACT greedy parity with ``fused_kernels`` armed (bf16, int8,
speculative verify), the loud-but-typed fallback on unsupported configs
(never wrong results), cost-registry rows proving the HBM-bytes
reduction, and the perf_gate wiring for the two new gated fields. Heavy
shapes ride behind ``slow``."""

import json

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.core.flags import set_flags
from paddlepaddle_tpu.inference.decode_engine import BatchDecodeEngine
from paddlepaddle_tpu.inference.serving import GenerationRequest


def _model(dtype="bfloat16", max_len=96):
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=192,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=max_len, dtype=dtype))


@pytest.fixture(scope="module")
def model():
    return _model()


def _reqs(prompts, specs):
    out = []
    for p, (_, mx, e) in zip(prompts, specs):
        r = GenerationRequest(p, mx, 0.0, 0, e)
        r.prefix_len = None
        out.append(r)
    return out


def _serve(eng, reqs):
    eng.serve(reqs, timeout=240)
    return [np.asarray(r.result.result(5)) for r in reqs]


SPECS = [(5, 8, None), (17, 4, None), (3, 10, 7), (40, 6, None)]


def _prompts(seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 128, (n,)).astype(np.int32)
            for n, _, _ in SPECS]


# -- gather-GEMM: kernel + dispatch parity -----------------------------------

def test_gather_gemm_parity_planted_ragged_loads():
    """Fused gather-GEMM vs the sorted capacity path (bitwise-identical
    routing, the drop-semantics twin) and vs the einsum one-hot dispatch
    (the independent reference), on PLANTED logits that force ragged
    loads: one overloaded expert past capacity (drops), one empty expert,
    and a long uniform tail. Gradients route through the reference
    formulation and must match it exactly."""
    import jax
    import jax.numpy as jnp

    from paddlepaddle_tpu.parallel.moe import (
        _fused_gather_gemm_moe_ffn,
        _gathered_capacity_moe_ffn,
        _topk_routing,
    )

    rng = np.random.default_rng(0)
    T, d, h, E, k, cap = 48, 16, 24, 4, 2, 8
    x = jnp.asarray(rng.standard_normal((T, d)), jnp.float32)
    # planted routing: half the tokens pile onto expert 0 (capacity
    # overflow -> drops), expert 3 receives NOTHING (empty group), the
    # rest spread over experts 1-2
    logits = np.full((T, E), -8.0, np.float32)
    logits[: T // 2, 0] = 8.0
    logits[: T // 2, 1] = 4.0
    logits[T // 2:, 1] = 8.0
    logits[T // 2:, 2] = 4.0
    logits = jnp.asarray(logits)
    wg = jnp.asarray(rng.standard_normal((E, d, h)) / 8, jnp.float32)
    wu = jnp.asarray(rng.standard_normal((E, d, h)) / 8, jnp.float32)
    wd = jnp.asarray(rng.standard_normal((E, h, d)) / 8, jnp.float32)

    ys, _ = jax.jit(lambda *a: _gathered_capacity_moe_ffn(*a, k, cap))(
        x, logits, wg, wu, wd)
    yf, af = jax.jit(lambda *a: _fused_gather_gemm_moe_ffn(*a, k, cap))(
        x, logits, wg, wu, wd)
    np.testing.assert_array_equal(np.asarray(ys), np.asarray(yf))

    # independent reference: the GShard one-hot einsum dispatch
    disp, comb, aux_e = _topk_routing(logits, cap, k)
    xin = jnp.einsum("tec,td->ecd", disp, x)
    gu = jax.nn.silu(jnp.einsum("ecd,edh->ech", xin, wg))
    out = jnp.einsum("ech,ehd->ecd", gu * jnp.einsum(
        "ecd,edh->ech", xin, wu), wd)
    ye = jnp.einsum("tec,ecd->td", comb, out)
    np.testing.assert_allclose(np.asarray(yf), np.asarray(ye), atol=1e-4)
    np.testing.assert_allclose(float(af), float(aux_e), rtol=1e-5)

    def loss(ffn):
        def f(x, wg, wu, wd):
            y, aux = ffn(x, logits, wg, wu, wd, k, cap)
            return jnp.sum(y ** 2) + aux

        return jax.jit(jax.grad(f, argnums=(0, 1, 2, 3)))

    gr = loss(_gathered_capacity_moe_ffn)(x, wg, wu, wd)
    gf = loss(_fused_gather_gemm_moe_ffn)(x, wg, wu, wd)
    for a, b in zip(gr, gf):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_moe_layer_fused_mode_and_loud_fallback(capsys):
    """``dispatch_mode="fused"`` through the full MoELayer matches the
    sorted layer weight-for-weight; with the kernel flag off the layer
    falls back LOUDLY to 'sorted' — one stderr line, correct results."""
    from paddlepaddle_tpu.parallel.moe import GShardGate, MoELayer

    x = np.random.default_rng(0).standard_normal((2, 8, 16)).astype(
        np.float32)
    paddle.seed(3)
    m_f = MoELayer(16, 32, 4, gate=GShardGate(16, 4), capacity_factor=2.0,
                   dispatch_mode="fused")
    assert m_f.dispatch_mode == "fused"
    paddle.seed(3)
    m_s = MoELayer(16, 32, 4, gate=GShardGate(16, 4), capacity_factor=2.0,
                   dispatch_mode="sorted")
    for (_, p1), (_, p2) in zip(sorted(m_f.raw_state().items()),
                                sorted(m_s.raw_state().items())):
        p2._replace_data(p1._data)
    np.testing.assert_array_equal(m_f(x).numpy(), m_s(x).numpy())

    set_flags({"FLAGS_fused_gather_gemm": False})
    try:
        capsys.readouterr()
        paddle.seed(3)
        m_fb = MoELayer(16, 32, 4, gate=GShardGate(16, 4),
                        capacity_factor=2.0, dispatch_mode="fused")
        assert m_fb.dispatch_mode == "sorted"
        assert "falling back to 'sorted'" in capsys.readouterr().err
        np.testing.assert_array_equal(m_fb(x).numpy(), m_s(x).numpy())
    finally:
        set_flags({"FLAGS_fused_gather_gemm": True})
    with pytest.raises(ValueError, match="dispatch_mode"):
        MoELayer(16, 32, 4, dispatch_mode="banana")


# -- paged attention: kernel unit parity -------------------------------------

@pytest.mark.parametrize("W,dtype", [(1, np.float32), (3, "bfloat16")])
def test_paged_attention_kernel_vs_reference_view(W, dtype):
    """The kernel vs the reference gather-view formulation, W=1 (chunked
    decode) and W=3 (the speculative k+1 verify shape), ragged lens
    incl. a zero-length (retired) slot and a non-page-aligned tail."""
    import jax
    import jax.numpy as jnp

    from paddlepaddle_tpu.ops.kernels.paged_attention import paged_attention

    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    S, P, ps, kvh, hd, h = 4, 3, 8, 2, 16, 4
    rep = h // kvh
    pages = 1 + S * P
    rng = np.random.default_rng(1)
    kp = jnp.asarray(rng.standard_normal((pages, ps, kvh, hd)), dt)
    vp = jnp.asarray(rng.standard_normal((pages, ps, kvh, hd)), dt)
    pt = jnp.asarray(rng.permutation(np.arange(1, pages))[: S * P]
                     .reshape(S, P), jnp.int32)
    pt = pt.at[3].set(0)                       # retired slot: zeroed row
    lens = jnp.asarray([5, 13, 20, 0], jnp.int32)   # 13, 20: mid-page tails
    q = jnp.asarray(rng.standard_normal((S, W, h, hd)), dt)

    out = jax.jit(lambda *a: paged_attention(
        *a, rep=rep, scale=1.0 / np.sqrt(hd)))(q, kp, vp, pt, lens)

    # reference: materialize the gathered view, mask, one softmax
    kview = kp[pt].reshape(S, P * ps, kvh, hd).astype(jnp.float32)
    vview = vp[pt].reshape(S, P * ps, kvh, hd).astype(jnp.float32)
    qg = q.reshape(S, W, kvh, rep, hd).astype(jnp.float32)
    logits = jnp.einsum("swkrd,slkd->skrwl", qg, kview) / np.sqrt(hd)
    k_pos = jnp.arange(P * ps)[None, None, None, None, :]
    q_pos = (lens[:, None] + jnp.arange(W)[None, :]
             )[:, None, None, :, None]
    logits = jnp.where(k_pos <= q_pos, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    ref = jnp.einsum("skrwl,slkd->swkrd", probs, vview).reshape(
        S, W, h, hd).astype(dt)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=(2e-2 if dtype == "bfloat16" else 2e-6))


# -- engine-level token-exact parity with the flag armed ---------------------

def test_engine_greedy_parity_and_hbm_reduction(model):
    """TOKEN-EXACT greedy parity, fused vs reference engine, ragged
    prompts/budgets/eos — the tentpole acceptance bar — plus the
    cost-registry proof: the fused decode program's lowered HBM bytes
    must be BELOW the reference formulation's (the gather it deletes)."""
    import jax

    from paddlepaddle_tpu.observability.perf import costs

    prompts = _prompts()

    def run(fused):
        eng = BatchDecodeEngine(model, max_slots=3, chunk=4, page_size=16,
                                fused_kernels=fused)
        outs = _serve(eng, _reqs(prompts, SPECS))
        return eng, outs

    ref_eng, ref = run(False)
    fus_eng, fus = run(True)
    assert fus_eng.fused_info() == {"enabled": True,
                                    "paged_attention": "interpret"}
    for a, b in zip(ref, fus):
        np.testing.assert_array_equal(a, b)

    # lowering-only cost rows (no backend compile): bytes saved is the
    # acceptance criterion the PR 6 plane verifies
    rows = {}
    for tag, eng in (("ref", ref_eng), ("fused", fus_eng)):
        c = costs.cost_of_lowered(
            "test.decode", jax.jit(eng._decode_program(1)),
            eng._decode_args(), bucket=tag, record=False)
        assert c is not None and c["bytes_accessed"]
        rows[tag] = c["bytes_accessed"]
    assert rows["fused"] < rows["ref"], \
        f"fused program must read fewer HBM bytes ({rows})"


def test_engine_spec_verify_parity_fused(model):
    """The speculative verify program (W=k+1 through the SAME fused
    forward) stays token-exact vs the reference engine."""
    prompts = _prompts(seed=1)

    def run(fused):
        eng = BatchDecodeEngine(model, max_slots=3, chunk=8, page_size=16,
                                draft=model, spec_k=2, fused_kernels=fused)
        return _serve(eng, _reqs(prompts, SPECS))

    for a, b in zip(run(False), run(True)):
        np.testing.assert_array_equal(a, b)


def test_engine_int8_parity_fused(model):
    """Weight-only int8 decode (projections read QuantizedWeight leaves
    inside the fused layer loop) stays token-exact vs reference."""
    prompts = _prompts(seed=2)

    def run(fused):
        eng = BatchDecodeEngine(model, max_slots=3, chunk=4, page_size=16,
                                quant="weight_only_int8",
                                fused_kernels=fused)
        return _serve(eng, _reqs(prompts, SPECS))

    for a, b in zip(run(False), run(True)):
        np.testing.assert_array_equal(a, b)


# -- fallback drill: unsupported config sheds to the reference path ----------

def test_fused_fallback_unsupported_config_never_wrong_results(model,
                                                               capsys):
    """The chaos drill: ``fused_kernels=True`` on an UNSUPPORTED config
    (page_size not sublane-aligned) must (a) announce the fallback on
    stderr with the reason, (b) surface it in fused_info/health and the
    compile-plan facts, and (c) serve results IDENTICAL to the reference
    engine — a fallback is never a silent behavior change and never
    wrong results."""
    prompts = _prompts(seed=3)
    capsys.readouterr()
    eng = BatchDecodeEngine(model, max_slots=3, chunk=4, page_size=12,
                            fused_kernels=True)
    err = capsys.readouterr().err
    assert "fused paged-attention kernel unavailable" in err
    info = eng.fused_info()
    assert info["enabled"] is False
    assert info["paged_attention"].startswith("fallback:")
    assert "page_size 12" in info["paged_attention"]
    # the compile-plan FACT is the PROGRAM identity, not the reason: a
    # fallback engine compiles byte-identical reference programs, so its
    # fingerprint must EQUAL an off engine's (bundles stay interchangeable
    # — arming the flag fleet-wide must not orphan reference bundles on
    # replicas that fall back) while a truly fused engine's differs
    assert eng.compile_plan.facts["fused"] == {
        "paged_attention": "reference"}
    ref = BatchDecodeEngine(model, max_slots=3, chunk=4, page_size=12,
                            fused_kernels=False)
    assert eng.compile_plan.fingerprint() \
        == ref.compile_plan.fingerprint()
    for a, b in zip(_serve(ref, _reqs(prompts, SPECS)),
                    _serve(eng, _reqs(prompts, SPECS))):
        np.testing.assert_array_equal(a, b)
    # contiguous layout: no page table to walk — also a typed fallback
    eng_c = BatchDecodeEngine(model, max_slots=2, chunk=4,
                              kv_layout="contiguous", fused_kernels=True)
    assert eng_c.fused_info()["paged_attention"].startswith(
        "fallback: kv_layout contiguous")


# -- perf_gate wiring for the two new fields ---------------------------------

def test_perf_gate_fused_fields(tmp_path):
    """The run_tier1 perf_gate smoke for the new gated fields:
    moe.dispatch_ms and serving.paged_chunk_overhead_pct regress at the
    latency budget, pass at parity."""
    import sys

    sys.path.insert(0, "tools")
    import perf_gate

    def write(name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    moe_base = write("mb.json", {"moe_dispatch": {"dispatch_ms": 10.0,
                                                  "fused_ms": 11.0}})
    moe_bad = write("mc.json", {"moe_dispatch": {"dispatch_ms": 15.0,
                                                 "fused_ms": 11.0}})
    assert perf_gate.main(["--baseline", moe_base,
                           "--current", moe_base]) == 0
    assert perf_gate.main(["--baseline", moe_base,
                           "--current", moe_bad]) == 1
    s_base = write("sb.json", {"serving_bench": {
        "aggregate_tok_s": 100, "paged_chunk_overhead_pct": 3.0}})
    s_bad = write("sc.json", {"serving_bench": {
        "aggregate_tok_s": 100, "paged_chunk_overhead_pct": 9.0}})
    assert perf_gate.main(["--baseline", moe_base, "--serving",
                           s_base, s_base]) == 0
    assert perf_gate.main(["--baseline", moe_base, "--serving",
                           s_bad, s_base]) == 1


# -- heavy shapes ------------------------------------------------------------

@pytest.mark.slow
def test_paged_attention_kernel_heavy_shapes():
    """Larger-shape kernel sweep: gqa rep 4, head_dim 64, W=5, 8 pages
    of 16 — the shapes the compiled TPU kernel would see."""
    import jax
    import jax.numpy as jnp

    from paddlepaddle_tpu.ops.kernels.paged_attention import paged_attention

    S, P, ps, kvh, hd, h, W = 8, 8, 16, 4, 64, 16, 5
    rep = h // kvh
    pages = 1 + S * P
    rng = np.random.default_rng(7)
    kp = jnp.asarray(rng.standard_normal((pages, ps, kvh, hd)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((pages, ps, kvh, hd)), jnp.bfloat16)
    pt = jnp.asarray(rng.permutation(np.arange(1, pages))[: S * P]
                     .reshape(S, P), jnp.int32)
    lens = jnp.asarray(rng.integers(0, P * ps - W, (S,)), jnp.int32)
    q = jnp.asarray(rng.standard_normal((S, W, h, hd)), jnp.bfloat16)
    out = jax.jit(lambda *a: paged_attention(
        *a, rep=rep, scale=1.0 / np.sqrt(hd)))(q, kp, vp, pt, lens)
    kview = kp[pt].reshape(S, P * ps, kvh, hd).astype(jnp.float32)
    vview = vp[pt].reshape(S, P * ps, kvh, hd).astype(jnp.float32)
    qg = q.reshape(S, W, kvh, rep, hd).astype(jnp.float32)
    logits = jnp.einsum("swkrd,slkd->skrwl", qg, kview) / np.sqrt(hd)
    k_pos = jnp.arange(P * ps)[None, None, None, None, :]
    q_pos = (lens[:, None] + jnp.arange(W)[None, :]
             )[:, None, None, :, None]
    logits = jnp.where(k_pos <= q_pos, logits, -1e30)
    ref = jnp.einsum("skrwl,slkd->swkrd", jax.nn.softmax(logits, -1),
                     vview).reshape(S, W, h, hd)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


@pytest.mark.slow
def test_engine_int8_groupwise_parity_fused():
    """int8 group-size 16 (the scale layout with per-group partial
    einsums) through the fused layer loop, token-exact. Seed chosen
    tie-free: online-softmax f32 rounding differs from the one-shot
    softmax by ~1e-7, which random-weight tiny models (near-uniform
    logits) can surface as an argmax flip — real checkpoints' logit
    margins sit orders of magnitude above it (docs/kernels.md)."""
    m = _model()
    prompts = _prompts(seed=6)

    def run(fused):
        eng = BatchDecodeEngine(m, max_slots=3, chunk=4, page_size=16,
                                quant="weight_only_int8",
                                quant_group_size=16, fused_kernels=fused)
        return _serve(eng, _reqs(prompts, SPECS))

    for a, b in zip(run(False), run(True)):
        np.testing.assert_array_equal(a, b)
