"""The COMPOSED 4D hybrid: dp × fsdp × tp × pp in ONE mesh running real
transformer blocks (attention + MLP + remat) through spmd_pipeline_train.

Reference surface: fleet/base/topology.py:189 HybridCommunicateGroup composes
data × pipe × sharding × model in one runtime; the end-to-end recipe is
test/auto_parallel/hybrid_strategy/semi_auto_llama.py. Here the parity oracle
is the unsharded single-device forward (parallel.hybrid.reference_forward):
loss AND per-leaf gradients must match across the 4-axis decomposition.
"""

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; fast tier covers this module via test_fast_smokes.py

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddlepaddle_tpu.parallel.hybrid import (
    HybridStageConfig, init_llama_head, init_llama_stage, llama_head_specs,
    llama_stage_specs, make_llama_block, make_vocab_parallel_head,
    reference_forward)
from paddlepaddle_tpu.parallel.pipeline_spmd import (
    spmd_pipeline_train, stack_stage_params, stack_virtual_stage_params)

CFG = HybridStageConfig(hidden_size=32, intermediate_size=64, num_heads=4,
                        num_kv_heads=2, layers_per_stage=1, vocab_size=64,
                        max_seq_len=16)


def _mesh4(dp=1, fsdp=2, tp=2, pp=2):
    devs = np.array(jax.devices()[: dp * fsdp * tp * pp])
    return Mesh(devs.reshape(dp, fsdp, tp, pp), ("dp", "fsdp", "tp", "pp"))


def _problem(n_stages, seed=0, batch=8, seq=16, cfg=CFG):
    keys = jax.random.split(jax.random.PRNGKey(seed), n_stages + 3)
    stages = [init_llama_stage(cfg, keys[i]) for i in range(n_stages)]
    head = init_llama_head(cfg, keys[n_stages])
    embed = jax.random.normal(keys[n_stages + 1],
                              (cfg.vocab_size, cfg.hidden_size), jnp.float32)
    ids = jax.random.randint(keys[n_stages + 2], (batch, seq), 0,
                             cfg.vocab_size, jnp.int32)
    acts = embed[ids]
    return stages, head, acts, ids


def _reference(stages, head, acts, labels, cfg=CFG):
    def f(st, hp, a):
        return reference_forward(cfg, st, hp, a, labels)

    loss, (g_st, g_h, g_a) = jax.value_and_grad(f, argnums=(0, 1, 2))(
        stages, head, acts)
    return loss, g_st, g_h, g_a


def _assert_tree_close(got, want, rtol=2e-3, atol=2e-4, what=""):
    for (kp, g), (_, w) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(want)[0], strict=True):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=rtol, atol=atol,
            err_msg=f"{what} mismatch at {jax.tree_util.keystr(kp)}")


@pytest.mark.parametrize("dp,fsdp,sched", [(1, 2, "1f1b"), (2, 1, "1f1b"),
                                           (1, 2, "zbh1")])
def test_4d_hybrid_schedule_matches_unpipelined(dp, fsdp, sched):
    """dp×fsdp×tp2×pp2 (both data-axis splits, 1F1B AND the zero-bubble
    ZBH1 split-backward schedule): loss, stage grads (fsdp
    reduce-scattered), head grads (vocab-parallel), and embedding cotangent
    all match the unsharded single-device oracle — ZBH1's BX/BW split ops
    re-linearize REAL transformer blocks here, not toy matmuls."""
    mesh = _mesh4(dp=dp, fsdp=fsdp)
    stages, head, acts, ids = _problem(n_stages=2)
    block = make_llama_block(CFG, remat=True)
    head_fn = make_vocab_parallel_head(CFG)

    loss, g_st, g_h, dacts = spmd_pipeline_train(
        stack_stage_params(stages), head, acts, ids, block, head_fn, mesh,
        schedule=sched, n_microbatches=4, pp_axis="pp",
        data_axis=("dp", "fsdp"), param_specs=llama_stage_specs(),
        head_specs=llama_head_specs())

    ref_loss, ref_st, ref_h, ref_a = _reference(stages, head, acts, ids)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    _assert_tree_close(g_st, stack_stage_params(ref_st), what="stage grads")
    _assert_tree_close(g_h, ref_h, what="head grads")
    _assert_tree_close(dacts, ref_a, what="embed cotangent")


@pytest.mark.parametrize("sched,seed", [("interleaved", 1), ("zbvpp", 3)])
def test_4d_hybrid_interleaved_schedules(sched, seed):
    """The composition under the V=2 interleaved schedules — plain VPP and
    ZBVPP (zero-bubble, r4, split BX/BW re-linearizing each chunk): 4
    virtual stages of REAL transformer blocks on pp=2 devices, grads vs
    the unsharded oracle."""
    mesh = _mesh4()
    stages, head, acts, ids = _problem(n_stages=4, seed=seed)
    block = make_llama_block(CFG, remat=True)
    head_fn = make_vocab_parallel_head(CFG)

    loss, g_st, g_h, dacts = spmd_pipeline_train(
        stack_virtual_stage_params(stages, 2), head, acts, ids, block,
        head_fn, mesh, schedule=sched, n_microbatches=4,
        num_virtual=2, pp_axis="pp", data_axis=("dp", "fsdp"),
        param_specs=llama_stage_specs(), head_specs=llama_head_specs())

    ref_loss, ref_st, ref_h, ref_a = _reference(stages, head, acts, ids)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    want = jax.tree_util.tree_map(
        lambda a: a.reshape((2, 2) + a.shape[1:]), stack_stage_params(ref_st))
    _assert_tree_close(g_st, want, what="stage grads")
    _assert_tree_close(g_h, ref_h, what="head grads")
    _assert_tree_close(dacts, ref_a, what="embed cotangent")


def test_hybrid_block_matches_llama_decoder_layer():
    """The functional stage block IS the Llama math: one unsharded
    make_llama_block layer must reproduce models.llama.LlamaDecoderLayer
    bit-for-tolerance on the same weights (closes the shared-oracle blind
    spot — reference_forward reuses the block, so this pins it to the
    actual model)."""
    from paddlepaddle_tpu.models.llama import LlamaConfig, LlamaDecoderLayer

    lcfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                       num_hidden_layers=1, num_attention_heads=4,
                       num_key_value_heads=2, max_position_embeddings=16)
    layer = LlamaDecoderLayer(lcfg)
    sp = {k: jnp.asarray(v) for k, v in {
        "ln1": layer.input_layernorm.weight.numpy()[None],
        "ln2": layer.post_attention_layernorm.weight.numpy()[None],
        "wq": layer.self_attn.q_proj.weight.numpy()[None],
        "wk": layer.self_attn.k_proj.weight.numpy()[None],
        "wv": layer.self_attn.v_proj.weight.numpy()[None],
        "wo": layer.self_attn.o_proj.weight.numpy()[None],
        "wg": layer.mlp.gate_proj.weight.numpy()[None],
        "wu": layer.mlp.up_proj.weight.numpy()[None],
        "wd": layer.mlp.down_proj.weight.numpy()[None],
    }.items()}
    block = make_llama_block(CFG, tp_axis=None, fsdp_axis=None, remat=False)

    import paddlepaddle_tpu as paddle

    x = np.random.default_rng(0).standard_normal((2, 16, 32)).astype(np.float32)
    from paddlepaddle_tpu.models.llama import _rope_cos_sin

    cos, sin = _rope_cos_sin(lcfg)
    want = layer(paddle.to_tensor(x), paddle.to_tensor(cos),
                 paddle.to_tensor(sin)).numpy()
    got = np.asarray(block(sp, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_4d_tp_collectives_stay_inside_stages():
    """The tp psums and fsdp all-gathers ride inside the scan's while body:
    the compiled collective count must NOT scale with the microbatch count,
    and the ring is exactly two collective-permutes."""

    def lower_text(M):
        mesh = _mesh4()
        stages, head, acts, ids = _problem(n_stages=2, batch=16)
        block = make_llama_block(CFG, remat=True)
        head_fn = make_vocab_parallel_head(CFG)

        def run(sp, hp, a, i):
            return spmd_pipeline_train(
                sp, hp, a, i, block, head_fn, mesh, schedule="1f1b",
                n_microbatches=M, pp_axis="pp", data_axis=("dp", "fsdp"),
                param_specs=llama_stage_specs(), head_specs=llama_head_specs())

        return jax.jit(run).lower(stack_stage_params(stages), head, acts,
                                  ids).compile().as_text()

    t4, t8 = lower_text(4), lower_text(8)

    def counts(txt):
        return {op: txt.count(op) for op in
                ("all-reduce(", "all-gather(", "collective-permute(")}

    c4, c8 = counts(t4), counts(t8)
    assert c4 == c8, (
        f"collective count scales with microbatches — not inside the scan "
        f"body: M=4 {c4} vs M=8 {c8}")
    assert c4["collective-permute("] == 2, c4
    # tp must never unshard a weight: no all-gather may produce the FULL
    # column-parallel width (h x 3h intermediate = 32x64 here); the fsdp
    # gathers produce [L, h, f_local/tp] slices only
    full_w = f"f32[1,{CFG.hidden_size},{CFG.intermediate_size}]"
    for line in t4.splitlines():
        if "all-gather(" in line and full_w in line:
            pytest.fail(f"tp-width weight fully gathered: {line.strip()[:140]}")


def test_5d_hybrid_with_allgather_kv_context_parallel():
    """The FULL 5-D composition in one mesh — dp x fsdp x tp x pp x sp —
    with allgather-KV blockwise context-parallel attention over the sp axis
    inside each pipeline stage (ppermute-based ring attention is not
    branch-safe inside the schedule executor — see
    hybrid._sp_blockwise_attention) and the cross-shard label shift in the
    vocab-parallel head. Loss and every gradient must match the unsharded
    oracle."""
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs.reshape(1, 1, 2, 2, 2), ("dp", "fsdp", "sp", "tp", "pp"))
    stages, head, acts, ids = _problem(n_stages=2, seed=2)
    block = make_llama_block(CFG, sp_axis="sp", sp_size=2, remat=True)
    head_fn = make_vocab_parallel_head(CFG, sp_axis="sp")

    @jax.jit
    def run(sp, hp, a, i):
        return spmd_pipeline_train(
            sp, hp, a, i, block, head_fn, mesh,
            schedule="1f1b", n_microbatches=4, pp_axis="pp",
            data_axis=("dp", "fsdp"), seq_axis="sp",
            param_specs=llama_stage_specs(), head_specs=llama_head_specs())

    loss, g_st, g_h, dacts = run(stack_stage_params(stages), head, acts, ids)

    ref_loss, ref_st, ref_h, ref_a = _reference(stages, head, acts, ids)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    _assert_tree_close(g_st, stack_stage_params(ref_st), what="stage grads")
    _assert_tree_close(g_h, ref_h, what="head grads")
    _assert_tree_close(dacts, ref_a, what="embed cotangent")


def test_moe_experts_inside_pipeline_stages():
    """Expert parallelism COMPOSED with the pipeline (+tp): one
    dp x fsdp x ep x tp x pp mesh runs MoE transformer stages through the
    1F1B executor — the ERNIE/DeepSeek hybrid layout (fleet topology +
    incubate moe_layer). Loss and all grads (expert banks ep-sharded,
    router assembled across members) match the unsharded oracle."""
    from paddlepaddle_tpu.parallel.hybrid import (init_moe_stage,
                                                  make_moe_block,
                                                  moe_stage_specs)

    E, topk, eh = 4, 2, 48
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs.reshape(1, 1, 2, 2, 2), ("dp", "fsdp", "ep", "tp", "pp"))
    keys = jax.random.split(jax.random.PRNGKey(4), 5)
    stages = [init_moe_stage(CFG, keys[i], E, eh) for i in range(2)]
    head = init_llama_head(CFG, keys[2])
    embed = jax.random.normal(keys[3], (CFG.vocab_size, CFG.hidden_size),
                              jnp.float32)
    ids = jax.random.randint(keys[4], (8, 16), 0, CFG.vocab_size, jnp.int32)
    acts = embed[ids]

    block = make_moe_block(CFG, E, topk=topk, capacity_factor=8.0,
                           ep_size=2, remat=True)
    head_fn = make_vocab_parallel_head(CFG)

    loss, g_st, g_h, dacts = spmd_pipeline_train(
        stack_stage_params(stages), head, acts, ids, block, head_fn, mesh,
        schedule="1f1b", n_microbatches=4, pp_axis="pp",
        data_axis=("dp", "fsdp"), param_specs=moe_stage_specs(),
        head_specs=llama_head_specs())

    # oracle: same math, all axes off
    oracle_block = make_moe_block(CFG, E, topk=topk, capacity_factor=8.0,
                                  tp_axis=None, fsdp_axis=None, ep_axis=None,
                                  ep_size=1, remat=False)
    oracle_head = make_vocab_parallel_head(CFG, tp_axis=None)

    def oracle(st, hp, a):
        x = a
        for sp in st:
            x = oracle_block(sp, x)
        return oracle_head(hp, x, ids)

    ref_loss, (ref_st, ref_h, ref_a) = jax.value_and_grad(
        oracle, argnums=(0, 1, 2))(stages, head, acts)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    _assert_tree_close(g_st, stack_stage_params(ref_st), what="stage grads")
    _assert_tree_close(g_h, ref_h, what="head grads")
    _assert_tree_close(dacts, ref_a, what="embed cotangent")


def test_4d_hybrid_multi_layer_stages():
    """layers_per_stage > 1: the stage block scans over its layer dim with
    remat per layer — grads must still match the oracle."""
    cfg2 = CFG._replace(layers_per_stage=2)
    mesh = _mesh4()
    stages, head, acts, ids = _problem(n_stages=2, seed=7, cfg=cfg2)
    block = make_llama_block(cfg2, remat=True)
    head_fn = make_vocab_parallel_head(cfg2)

    loss, g_st, g_h, dacts = spmd_pipeline_train(
        stack_stage_params(stages), head, acts, ids, block, head_fn, mesh,
        schedule="1f1b", n_microbatches=4, pp_axis="pp",
        data_axis=("dp", "fsdp"), param_specs=llama_stage_specs(),
        head_specs=llama_head_specs())

    ref_loss, ref_st, ref_h, ref_a = _reference(stages, head, acts, ids,
                                                cfg=cfg2)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    _assert_tree_close(g_st, stack_stage_params(ref_st), what="stage grads")
    _assert_tree_close(g_h, ref_h, what="head grads")
    _assert_tree_close(dacts, ref_a, what="embed cotangent")
