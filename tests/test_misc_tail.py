"""Round-4 audit-tail closure: linalg namespace + matrix_exp/fp8 gemm,
unique_name/dlpack/download, BFGS/LBFGS functional minimizers, asp
exclusions, ReduceLROnPlateau, cost_model, and the submodule aliases."""

import numpy as np
import pytest
import scipy.linalg

import paddlepaddle_tpu as paddle

rng = np.random.default_rng(21)


def test_linalg_namespace_and_matrix_exp():
    import ast
    import os

    if not os.path.exists("/root/reference"):
        pytest.skip("reference tree not present")
    tree = ast.parse(open("/root/reference/python/paddle/linalg.py").read())
    names = next([ast.literal_eval(e) for e in n.value.elts]
                 for n in ast.walk(tree)
                 if isinstance(n, ast.Assign)
                 and getattr(n.targets[0], "id", "") == "__all__")
    assert not [n for n in names if not hasattr(paddle.linalg, n)]

    A = (rng.standard_normal((4, 4)) * 0.3).astype(np.float32)
    np.testing.assert_allclose(
        paddle.linalg.matrix_exp(paddle.to_tensor(A)).numpy(),
        scipy.linalg.expm(A), rtol=1e-4, atol=1e-5)
    B = np.stack([A, 2 * A])                      # batched
    out = paddle.linalg.matrix_exp(paddle.to_tensor(B)).numpy()
    np.testing.assert_allclose(out[1], scipy.linalg.expm(2 * A),
                               rtol=1e-4, atol=1e-5)


def test_fp8_gemm():
    import ml_dtypes

    x = (rng.standard_normal((4, 8)) * 0.5).astype(ml_dtypes.float8_e4m3fn)
    y = (rng.standard_normal((8, 3)) * 0.5).astype(ml_dtypes.float8_e4m3fn)
    out = paddle.linalg.fp8_fp8_half_gemm_fused(
        paddle.to_tensor(x), paddle.to_tensor(y), scale=2.0)
    assert str(out.numpy().dtype) == "float16"
    ref = x.astype(np.float32) @ y.astype(np.float32) * 2.0
    np.testing.assert_allclose(out.numpy().astype(np.float32), ref,
                               rtol=2e-2, atol=2e-2)
    with pytest.raises(ValueError, match="float8"):
        paddle.linalg.fp8_fp8_half_gemm_fused(
            paddle.to_tensor(np.zeros((2, 2), np.float32)),
            paddle.to_tensor(np.zeros((2, 2), np.float32)))


def test_unique_name_and_download():
    un = paddle.utils.unique_name
    with un.guard():
        a = un.generate("fc")
        b = un.generate("fc")
        c = un.generate("conv")
    assert (a, b, c) == ("fc_0", "fc_1", "conv_0")
    with un.guard("p_"):
        assert un.generate("fc") == "p_fc_0"
    # outer scope unaffected by the guards
    with un.guard():
        assert un.generate("fc") == "fc_0"

    with pytest.raises(RuntimeError, match="zero egress"):
        paddle.utils.download.get_weights_path_from_url(
            "https://example.com/w.pdparams")


def test_dlpack_roundtrip_and_torch_interop():
    import torch

    t = paddle.to_tensor(rng.standard_normal((3, 4)).astype(np.float32))
    rt = paddle.utils.dlpack.from_dlpack(paddle.utils.dlpack.to_dlpack(t))
    np.testing.assert_array_equal(rt.numpy(), t.numpy())
    tt = torch.utils.dlpack.from_dlpack(paddle.utils.dlpack.to_dlpack(t))
    np.testing.assert_array_equal(tt.numpy(), t.numpy())
    back = paddle.utils.dlpack.from_dlpack(torch.ones(5))
    assert back.numpy().tolist() == [1.0] * 5
    # top-level aliases round-trip through the same implementation
    # (the old paddle.to_dlpack used a removed jax API — caught here)
    rt2 = paddle.from_dlpack(paddle.to_dlpack(t))
    np.testing.assert_array_equal(rt2.numpy(), t.numpy())


def test_minimize_bfgs_and_lbfgs():
    F = paddle.incubate.optimizer.functional
    target = np.array([1.0, -2.0, 3.0], np.float32)

    def quad(x):
        return ((x - paddle.to_tensor(target)) ** 2).sum()

    ok, calls, pos, val, grad, H = F.minimize_bfgs(
        quad, paddle.to_tensor(np.zeros(3, np.float32)))
    assert ok and int(calls.numpy()) > 0
    np.testing.assert_allclose(pos.numpy(), target, atol=1e-4)
    assert float(val.numpy()) < 1e-8
    assert H.shape == [3, 3]

    def rosen(x):
        return (1 - x[0]) ** 2 + 100 * (x[1] - x[0] ** 2) ** 2

    ok2, _, pos2, val2, g2 = F.minimize_lbfgs(
        rosen, paddle.to_tensor(np.array([-1.0, 1.0], np.float32)),
        max_iters=200)
    np.testing.assert_allclose(pos2.numpy(), [1.0, 1.0], atol=1e-2)
    with pytest.raises(NotImplementedError, match="strong_wolfe"):
        F.minimize_bfgs(quad, paddle.to_tensor(np.zeros(2, np.float32)),
                        line_search_fn="armijo")


def test_asp_excluded_and_supported_layers():
    from paddlepaddle_tpu.incubate import asp

    net = paddle.nn.Sequential(paddle.nn.Linear(16, 16),
                               paddle.nn.Linear(16, 16))
    names = [p.name for p in net.parameters() if p.ndim == 2]
    asp.set_excluded_layers([names[0]])
    try:
        pruned = asp.prune_model(net)
        pruned_names = {p.name for p in pruned}
        assert names[0] not in pruned_names and names[1] in pruned_names
    finally:
        asp.reset_excluded_layers()
    # after reset both prune
    pruned = asp.prune_model(net)
    assert {p.name for p in pruned} >= set(names)
    asp.add_supported_layer("whatever")           # parity surface


def test_reduce_lr_on_plateau():
    cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                            patience=2, verbose=0,
                                            cooldown=1, min_lr=0.01)

    class FakeModel:
        pass

    m = FakeModel()
    opt = paddle.optimizer.SGD(learning_rate=0.08,
                               parameters=[paddle.to_tensor([1.0])])
    m._optimizer = opt
    cb.set_model(m)
    cb.on_train_begin()
    # the reference triggers on EVAL end only (epoch-end would double
    # count monitors merged into the epoch logs)
    # e0 sets best; e1/e2 stale -> reduce to 0.04 (cooldown 1);
    # e3 cooldown tick then stale; e4 stale -> reduce to 0.02
    for _ in range(5):
        cb.on_eval_end({"loss": 1.0})
    assert abs(opt.get_lr() - 0.02) < 1e-9
    cb.on_epoch_end(7, {"loss": 1.0})     # epoch end must NOT count
    assert abs(opt.get_lr() - 0.02) < 1e-9
    # improvement resets the counter
    cb.on_eval_end({"loss": 0.1})
    cb.on_eval_end({"loss": 0.09})
    assert abs(opt.get_lr() - 0.02) < 1e-9
    # scheduler-composed lr scales the whole schedule, not compounding
    from paddlepaddle_tpu.optimizer.lr import StepDecay

    sched = StepDecay(learning_rate=0.08, step_size=100, gamma=0.1)
    opt2 = paddle.optimizer.SGD(learning_rate=sched,
                                parameters=[paddle.to_tensor([1.0])])
    m2 = FakeModel()
    m2._optimizer = opt2
    cb2 = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                             patience=1, verbose=0)
    cb2.set_model(m2)
    cb2.on_train_begin()
    for _ in range(3):
        cb2.on_eval_end({"loss": 1.0})
    # e0 best, e1 reduce (0.04), e2 reduce (0.02) at patience=1
    assert abs(opt2.get_lr() - 0.02) < 1e-9
    assert abs(sched.base_lr - 0.02) < 1e-9
    with pytest.raises(ValueError):
        paddle.callbacks.ReduceLROnPlateau(factor=1.5)
    # VisualDL/Wandb construct without their soft deps installed
    paddle.callbacks.VisualDL(log_dir="/tmp/vdl")
    paddle.callbacks.WandbCallback(project="x")


def test_cost_model_profiles_ops():
    cm = paddle.cost_model.CostModel()
    startup, main = cm.build_program()
    costs = cm.profile_measure(startup, main, device="cpu")
    paddle.disable_static()
    assert "total" in costs and costs["total"]["time"] > 0
    op_rows = {k: v for k, v in costs.items() if k != "total"}
    assert op_rows and all(v["count"] >= 1 for v in op_rows.values())
    assert sum(v["time"] for v in op_rows.values()) <= \
        costs["total"]["time"] * 1.01


def test_submodule_aliases():
    assert paddle.sparse.creation.sparse_coo_tensor is \
        paddle.sparse.sparse_coo_tensor
    assert paddle.nn.initializer.lazy_init.LazyGuard is paddle.LazyGuard
    with pytest.raises(NotImplementedError, match="XPU"):
        paddle.incubate.xpu.resnet_block.resnet_basic_block()


def test_asp_add_supported_layer_contract():
    """The shape gate already covers every registrable type (documented
    superset of the reference's type list); custom pruning funcs raise
    instead of being silently dropped."""
    from paddlepaddle_tpu.incubate import asp

    net = paddle.nn.Sequential(paddle.nn.Linear(6, 8))
    assert len(asp.prune_model(net)) == 1      # shape-gated: included
    asp.add_supported_layer(paddle.nn.Linear)  # recorded, no error
    assert "Linear" in asp._extra_supported
    asp._extra_supported.clear()
    with pytest.raises(NotImplementedError, match="mask_1d"):
        asp.add_supported_layer(paddle.nn.Linear, pruning_func=lambda w: w)


def test_reduce_lr_plateau_min_lr_with_scheduler():
    """min_lr holds through subsequent scheduler steps (base scales by
    the clamped effective ratio, not the raw factor)."""
    from paddlepaddle_tpu.optimizer.lr import StepDecay

    sched = StepDecay(learning_rate=0.08, step_size=1000, gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched,
                               parameters=[paddle.to_tensor([1.0])])

    class M:
        pass

    m = M()
    m._optimizer = opt
    cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                            patience=1, verbose=0,
                                            min_lr=0.05)
    cb.set_model(m)
    cb.on_train_begin()
    for _ in range(6):
        cb.on_eval_end({"loss": 1.0})
    assert abs(sched.last_lr - 0.05) < 1e-9
    sched.step()                                 # within step_size window
    assert sched.last_lr >= 0.05 - 1e-9
