"""BERT (BASELINE config 1) and ResNet (config 2) smoke + training tests."""

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; fast tier covers this module via test_fast_smokes.py

import numpy as np

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    resnet18,
)


def test_bert_forward_and_loss():
    m = BertForSequenceClassification(BertConfig.tiny())
    ids = np.random.default_rng(0).integers(0, 128, (2, 16)).astype(np.int32)
    logits = m(ids)
    assert logits.shape == [2, 2]
    labels = np.array([0, 1], np.int64)
    loss = m(ids, labels=labels)
    assert np.isfinite(float(loss.numpy()))


def test_bert_train_step_decreases():
    from paddlepaddle_tpu.jit.train import TrainStep
    from paddlepaddle_tpu.optimizer import AdamW

    m = BertForSequenceClassification(BertConfig.tiny())
    opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = TrainStep(m, opt, lambda mm, ids, labels: mm(ids, labels=labels))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (8, 16)).astype(np.int32)
    labels = rng.integers(0, 2, (8,)).astype(np.int64)
    losses = [float(step(ids, labels).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_bert_attention_mask():
    m = BertForSequenceClassification(BertConfig.tiny())
    m.eval()
    ids = np.random.default_rng(0).integers(0, 128, (1, 8)).astype(np.int32)
    mask = np.ones((1, 8), np.float32)
    out = m(ids, attention_mask=mask)
    assert np.isfinite(out.numpy()).all()


def test_resnet18_forward_train_eval():
    m = resnet18(num_classes=10)
    x = np.random.default_rng(0).standard_normal((2, 3, 32, 32)).astype(np.float32)
    out = m(x)
    assert out.shape == [2, 10]
    m.eval()
    out_eval = m(x)
    assert np.isfinite(out_eval.numpy()).all()


def test_resnet_backward():
    m = resnet18(num_classes=4)
    x = np.random.default_rng(0).standard_normal((2, 3, 32, 32)).astype(np.float32)
    labels = np.array([0, 1], np.int64)
    loss = paddle.nn.functional.cross_entropy(m(x), labels)
    loss.backward()
    g = m.conv1.weight.grad
    assert g is not None and float(np.abs(g.numpy()).sum()) > 0


def test_vision_zoo_extras_forward():
    from paddlepaddle_tpu.vision.models import (
        densenet121,
        shufflenet_v2_x0_5,
        squeezenet1_1,
    )

    x = np.random.default_rng(0).standard_normal((1, 3, 64, 64)).astype(np.float32)
    for net in (densenet121(num_classes=6), squeezenet1_1(num_classes=6),
                shufflenet_v2_x0_5(num_classes=6)):
        out = net(x)
        assert out.shape == [1, 6], type(net).__name__


def test_vision_zoo_variant_tail_forward():
    """The round-4 variant tail: every name in the reference's
    vision/models __all__ (python/paddle/vision/models/__init__.py:64)
    now resolves, and the new size/activation variants run forward."""
    import ast
    import os

    from paddlepaddle_tpu.vision import models as M

    ref = "/root/reference/python/paddle/vision/models/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference tree not present")
    tree = ast.parse(open(ref).read())
    names = next(
        [ast.literal_eval(e) for e in n.value.elts]
        for n in ast.walk(tree)
        if isinstance(n, ast.Assign)
        and getattr(n.targets[0], "id", "") == "__all__")
    missing = [n for n in names if not hasattr(M, n)]
    assert not missing, missing

    x = np.random.default_rng(1).standard_normal((1, 3, 32, 32)) \
        .astype(np.float32)
    for net in (M.shufflenet_v2_x0_25(num_classes=5),
                M.shufflenet_v2_swish(num_classes=5)):
        assert net(x).shape == [1, 5], type(net).__name__
    # config-level checks for the deep variants (forward would dominate
    # suite wall-clock on the CPU mesh without adding coverage)
    assert M.shufflenet_v2_x0_33().conv5[0].weight.shape[1] == 128
    d161 = M.densenet161(num_classes=3)
    assert d161.features_head[0].weight.shape[0] == 96   # wide: init 96
    d264 = M.densenet264(num_classes=3)
    # (6,12,64,48) blocks at growth 32 from init 64 -> 2688 final features
    assert d264.classifier.weight.shape[0] == 2688
    rx = M.resnext152_64x4d(num_classes=3)
    assert rx.layer1[0].conv2.weight.shape[0] == 256     # width 4 * 64


def test_googlenet_and_inception_v3_forward():
    """Round-4 zoo tail (reference python/paddle/vision/models/{googlenet,
    inceptionv3}.py): GoogLeNet returns (main, aux1, aux2) with aux heads
    active only in train mode; InceptionV3 runs the 299 input contract."""
    from paddlepaddle_tpu.vision.models import googlenet, inception_v3

    rng = np.random.default_rng(0)
    g = googlenet(num_classes=6)
    x = rng.standard_normal((1, 3, 224, 224)).astype(np.float32)
    g.eval()
    out, a1, a2 = g(x)
    assert out.shape == [1, 6] and a1 is None and a2 is None
    g.train()
    out, a1, a2 = g(x)
    assert out.shape == [1, 6] and a1.shape == [1, 6] and a2.shape == [1, 6]

    m = inception_v3(num_classes=6)
    m.eval()
    out = m(rng.standard_normal((1, 3, 299, 299)).astype(np.float32))
    assert out.shape == [1, 6]


def test_mobilenet_v1_and_v3_forward():
    """Zoo completion (reference mobilenetv1.py / mobilenetv3.py):
    depthwise-separable V1 and SE+hardswish V3 small/large."""
    from paddlepaddle_tpu.vision.models import (mobilenet_v1,
                                                mobilenet_v3_large,
                                                mobilenet_v3_small)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 3, 96, 96)).astype(np.float32)
    for net in (mobilenet_v1(num_classes=5, scale=0.5),
                mobilenet_v3_small(num_classes=5, scale=0.5),
                mobilenet_v3_large(num_classes=5, scale=0.5)):
        out = net(x)
        assert out.shape == [1, 5], type(net).__name__
