"""Long-tail nn.functional coverage: 3d pools, fold/grid_sample, losses,
conv transpose numerics vs torch."""

import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.nn import functional as F


def test_conv2d_transpose_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 3, 8, 8)).astype(np.float32)
    w = rng.standard_normal((3, 2, 4, 4)).astype(np.float32)
    for stride, pad in ((2, 1), (1, 0), (3, 2)):
        ref = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=stride, padding=pad).numpy()
        out = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                                 stride=stride, padding=pad)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_conv2d_transpose_groups_matches_torch():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 4, 6, 6)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)  # groups=2: out=6
    ref = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w), stride=2, groups=2).numpy()
    out = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w), stride=2, groups=2)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_conv1d_transpose_matches_torch():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((1, 3, 10)).astype(np.float32)
    w = rng.standard_normal((3, 2, 4)).astype(np.float32)
    ref = TF.conv_transpose1d(torch.tensor(x), torch.tensor(w), stride=2).numpy()
    out = F.conv1d_transpose(paddle.to_tensor(x), paddle.to_tensor(w), stride=2)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_pools_3d_and_adaptive():
    x3 = np.random.default_rng(0).standard_normal((1, 2, 8, 8, 8)).astype(np.float32)
    mp = F.max_pool3d(x3, 2)
    ref = TF.max_pool3d(torch.tensor(x3), 2).numpy()
    np.testing.assert_allclose(mp.numpy(), ref, rtol=1e-5)
    ap = F.avg_pool3d(x3, 2)
    ref = TF.avg_pool3d(torch.tensor(x3), 2).numpy()
    np.testing.assert_allclose(ap.numpy(), ref, rtol=1e-5)
    assert F.adaptive_avg_pool3d(x3, 2).shape == [1, 2, 2, 2, 2]
    x1 = np.random.default_rng(1).standard_normal((1, 3, 8)).astype(np.float32)
    assert F.adaptive_max_pool1d(x1, 4).shape == [1, 3, 4]


def test_pixel_shuffle_roundtrip_and_channel_shuffle():
    img = np.random.default_rng(0).standard_normal((1, 4, 8, 8)).astype(np.float32)
    pu = F.pixel_unshuffle(img, 2)
    assert pu.shape == [1, 16, 4, 4]
    np.testing.assert_allclose(F.pixel_shuffle(pu, 2).numpy(), img, atol=1e-6)
    np.testing.assert_allclose(
        F.channel_shuffle(img, 2).numpy(),
        TF.channel_shuffle(torch.tensor(img), 2).numpy(), atol=1e-6)


def test_grid_sample_identity_and_fold_roundtrip():
    img = np.random.default_rng(0).standard_normal((1, 4, 8, 8)).astype(np.float32)
    theta = np.array([[[1., 0., 0.], [0., 1., 0.]]], np.float32)
    grid = F.affine_grid(paddle.to_tensor(theta), [1, 4, 8, 8])
    out = F.grid_sample(paddle.to_tensor(img), grid)
    np.testing.assert_allclose(out.numpy(), img, atol=1e-4)
    u = F.unfold(paddle.to_tensor(img), [2, 2], strides=2)
    fb = F.fold(u, [8, 8], [2, 2], strides=2)
    np.testing.assert_allclose(fb.numpy(), img, atol=1e-5)


def test_new_losses_match_torch():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((4, 3)).astype(np.float32)
    b = rng.standard_normal((4, 3)).astype(np.float32)
    np.testing.assert_allclose(
        float(F.huber_loss(paddle.to_tensor(a), paddle.to_tensor(b), delta=1.0).numpy()),
        TF.huber_loss(torch.tensor(a), torch.tensor(b), delta=1.0).item(), rtol=1e-5)
    lb = np.sign(b).astype(np.float32)
    np.testing.assert_allclose(
        float(F.soft_margin_loss(paddle.to_tensor(a), paddle.to_tensor(lb)).numpy()),
        TF.soft_margin_loss(torch.tensor(a), torch.tensor(lb)).item(), rtol=1e-5)
    var = np.abs(b) + 0.1
    np.testing.assert_allclose(
        float(F.gaussian_nll_loss(paddle.to_tensor(a), paddle.to_tensor(b),
                                  paddle.to_tensor(var)).numpy()),
        TF.gaussian_nll_loss(torch.tensor(a), torch.tensor(b), torch.tensor(var)).item(),
        rtol=1e-4)
    np.testing.assert_allclose(
        float(F.poisson_nll_loss(paddle.to_tensor(a), paddle.to_tensor(np.abs(b))).numpy()),
        TF.poisson_nll_loss(torch.tensor(a), torch.tensor(np.abs(b))).item(), rtol=1e-5)
