"""Request-journey tracing (observability/reqtrace.py + the serving
seams that stamp into it).

The acceptance surface of ISSUE 12: one request through a 3-replica fleet
under a forced mid-flight kill yields ONE stitched journey — router pick
with candidate scores, the failed attempt with its cause, the successful
attempt, admission, decode — retrievable via the exporter's ``/requests``
endpoint and rendered by ``obsctl requests``; a speculative engine's
journey shows draft/verify rounds with acceptance; journeys are released
(ring-bounded, zero in-flight residue) after a soak; the SLO burn-rate
gauges feed ``health()``; and the router failover path stamps queue wait
PER ATTEMPT instead of reading from the first submit.

Most tests drive static fake-model fleets (no JAX compiles); one
continuous+speculative test uses a deliberately minimal tiny-Llama so the
whole module stays seconds-cheap in tier-1.
"""

import json
import threading
import time

import numpy as np
import pytest

from paddlepaddle_tpu.core import flags as _flags
from paddlepaddle_tpu.inference import (
    FleetUnavailableError,
    ReplicaClient,
    ServingEngine,
    ServingRouter,
)
from paddlepaddle_tpu.observability import reqtrace
from test_serving_robustness import FakeModel, _prompt


@pytest.fixture()
def traced():
    """Arm reqtrace with a small ring for the duration of one test and
    leave the process state clean afterwards."""
    reqtrace.reset()
    reqtrace.enable(ring=64)
    yield reqtrace
    reqtrace.disable()
    reqtrace.reset()


def _factory(model=None, **kw):
    kw.setdefault("mode", "static")
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_wait_ms", 2.0)
    kw.setdefault("max_len", 64)
    return lambda: ServingEngine(model() if callable(model)
                                 else (model or FakeModel()), **kw)


def _names(j):
    return [s["name"] for s in j.spans]


# -- the stitched failover journey -------------------------------------------

def test_midflight_kill_yields_one_stitched_journey(traced):
    """3-replica fleet, replica 0 dies mid-decode: the request's journey
    contains BOTH attempts — pick with candidate scores, the failed
    attempt tagged with the failure cause, the successful attempt, the
    winning replica's admission — and exactly one journey exists."""
    r = ServingRouter(
        [_factory(FakeModel(fail_next=1, delay_s=0.01)),
         _factory(FakeModel(delay_s=0.01)),
         _factory(FakeModel(delay_s=0.01))],
        probe_interval_s=60.0)
    try:
        fut = r.submit(_prompt(), max_new_tokens=2)
        assert fut.result(30).shape == (6,)
    finally:
        r.stop()
    js = traced.journeys()
    assert len(js) == 1 and not traced.inflight()
    j = js[0]
    assert j.done and j.outcome == "ok"
    assert j.attempts == 2 and j.replicas[0] == "r0"
    names = _names(j)
    for expected in ("submit", "router.pick", "queue.wait", "admit",
                     "router.attempt", "finish"):
        assert expected in names, (expected, names)
    picks = [s for s in j.spans if s["name"] == "router.pick"]
    assert len(picks) == 2
    assert "r0" in picks[0]["candidates"]          # candidate scores ride
    attempts = [s for s in j.spans if s["name"] == "router.attempt"]
    assert [a["ok"] for a in attempts] == [False, True]
    assert "synthetic decode failure" in attempts[0]["error"]
    assert attempts[0]["replica"] == "r0"
    assert attempts[1]["replica"] == j.replicas[1]
    # the winning replica's engine-side spans attribute to ITS track
    admits = [s for s in j.spans if s["name"] == "admit"]
    assert admits[-1]["replica"] == j.replicas[1]
    # the journey is the wrapper future's: slo numbers stitched in
    assert j.slo and j.slo["new_tokens"] == 2


def test_failover_queue_wait_is_stamped_per_attempt(traced):
    """The satellite fix: after a failover the wrapper's slo() queue wait
    reads from the WINNING attempt's own dispatch, not the first submit —
    the failed attempt's decode and the failover dance stay out of
    "queue wait" (they remain visible in TTFT and the attempt spans)."""
    class _BurnsThenDies:
        """Decode sleeps, THEN dies — the failed attempt costs real wall
        time, exactly the conflation the per-attempt stamp removes."""

        def generate_cached(self, ids, max_new_tokens, temperature=0.0,
                            top_k=0, eos_token_id=None):
            time.sleep(0.06)
            raise RuntimeError("synthetic decode failure")

    r = ServingRouter(
        [_factory(_BurnsThenDies(), max_batch_size=1),
         _factory(FakeModel(delay_s=0.01), max_batch_size=1)],
        probe_interval_s=60.0)
    try:
        fut = r.submit(_prompt(), max_new_tokens=2)
        fut.result(30)
    finally:
        r.stop()
    assert fut._t_dispatch is not None
    assert fut._t_dispatch > fut._t_submit     # attempt 2 dispatched later
    s = fut.slo()
    # attempt 1 burned >= 50 ms before failing over; the winning attempt's
    # queue wait is the few-ms admission path, far under that
    assert s["ttft_s"] >= 0.05
    assert s["queue_wait_s"] < s["ttft_s"] - 0.04, s
    # multi-token stamp also rides the copy (spec engines behind a router)
    assert fut._n_at_first == 1


def test_sync_refusal_closes_journey_no_leak(traced):
    """A submit that raises synchronously (fleet unavailable) never sets
    its future — the journey must still close (outcome rejected) instead
    of leaking into the in-flight map forever."""
    r = ServingRouter([_factory()], probe_interval_s=60.0,
                      breaker_reset_s=5.0)
    r.start()
    try:
        r._replicas[0].client.kill()
        for _ in range(3):
            r._probe_once()               # probes evict the dead replica
        with pytest.raises(FleetUnavailableError):
            r.submit(_prompt(), max_new_tokens=2)
    finally:
        r.stop()
    assert not traced.inflight()          # zero leaked journeys
    js = traced.journeys()
    assert js and js[-1].outcome == "rejected"
    reject = [s for s in js[-1].spans if s["name"] == "router.reject"]
    assert reject and reject[-1]["retryable"] is False


def test_trace_unaware_replica_client_still_serves(traced):
    """A replica client whose submit() predates the trace kwarg (remote
    implementations of the seam): the router drops the kwarg for that
    replica and serves — no breaker evidence burned, no failed request —
    so arming tracing can never take a fleet down."""

    class LegacyClient(ReplicaClient):
        def submit(self, prompt_ids, **kw):
            if "trace" in kw:
                raise TypeError(
                    "submit() got an unexpected keyword argument 'trace'")
            return super().submit(prompt_ids, **kw)

    r = ServingRouter([LegacyClient(_factory(), name="legacy")],
                      probe_interval_s=60.0)
    try:
        assert r.submit(_prompt(), max_new_tokens=2).result(30).shape == (6,)
        rep = r._replicas[0]
        assert rep.no_trace
        assert rep.breaker.state == "closed"
        assert r.stats["failed"] == 0
    finally:
        r.stop()
    j = traced.journeys()[-1]
    assert j.outcome == "ok" and j.attempts == 1   # retry was invisible
    picks = [s for s in j.spans if s["name"] == "router.pick"]
    assert len(picks) == 1                         # undone pick un-stamped


def test_reqtrace_off_costs_nothing_and_records_nothing():
    reqtrace.reset()
    assert not reqtrace.enabled()
    eng = _factory()()
    try:
        fut = eng.submit(_prompt(), max_new_tokens=2)
        fut.result(30)
    finally:
        eng.stop()
    assert fut._trace is None
    assert not reqtrace.journeys() and not reqtrace.inflight()


# -- ring bounds / release ---------------------------------------------------

def test_soak_releases_journeys_ring_bounded(traced):
    """200-request soak: every journey is closed (zero in-flight
    residue), the ring holds at most its capacity, and per-journey span
    caps hold — no growth anywhere."""
    eng = _factory(max_batch_size=4)()
    try:
        futs = [eng.submit(_prompt(v=i % 5), max_new_tokens=2)
                for i in range(200)]
        for f in futs:
            f.result(60)
    finally:
        eng.stop()
    assert not traced.inflight()               # all released
    js = traced.journeys()
    assert len(js) == 64                       # ring-bounded (cap 64)
    assert all(j.done for j in js)
    assert all(len(j.spans) <= j.max_spans for j in js)
    doc = traced.requests_jsonable()
    assert doc["inflight_count"] == 0 and len(doc["journeys"]) == 64
    # exemplars stay joinable: every row's trace_id resolves in the ring
    # (rows for ring-evicted journeys are pruned, not left dangling)
    ring_ids = {j.trace_id for j in js}
    for block in traced.exemplars().values():
        for row in block["slowest"]:
            assert row["trace_id"] in ring_ids


def test_span_cap_counts_drops_instead_of_growing(traced):
    j = traced.mint(1)
    j.max_spans = 8
    for i in range(50):
        j.event("decode.chunk", tokens=1)
    assert len(j.spans) == 8 and j.dropped == 42


# -- speculative engine journey ----------------------------------------------

def test_spec_engine_journey_shows_rounds_with_acceptance(traced):
    """A (self-draft) speculative engine's journey carries the
    draft-prefill event and per-chunk spec.round spans whose
    proposed/accepted counts reconcile with full self-acceptance."""
    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=32, hidden_size=16, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=48, dtype="bfloat16"))
    eng = ServingEngine(model, max_batch_size=1, decode_chunk=6,
                        kv_page_size=16, max_len=48, draft=model, spec_k=2)
    try:
        p = np.arange(6, dtype=np.int32) % 32
        fut = eng.submit(p, max_new_tokens=7)
        out = fut.result(300)
        assert out.shape == (13,)
    finally:
        eng.stop()
    js = traced.journeys()
    assert len(js) == 1
    j = js[0]
    names = _names(j)
    assert "spec.draft_prefill" in names
    assert "admit" in names and "first_token" in names
    rounds = [s for s in j.spans if s["name"] == "spec.round"]
    assert rounds, names
    for s in rounds:
        assert s["k"] == 2
        assert s["proposed"] == s["steps"] * 2
        # self-draft: the draft IS the target, greedy acceptance is total
        assert s["accepted"] == s["proposed"]
    # admission recorded its page reservation (paged pool)
    admit = next(s for s in j.spans if s["name"] == "admit")
    assert admit["pages"] >= 1 and admit["bucket"] == 48


# -- /requests endpoint + obsctl + exemplars ---------------------------------

def test_requests_endpoint_obsctl_and_exemplars(traced, capsys):
    """The journey is retrievable via /requests (strict JSON), reachable
    FROM the TTFT-histogram exemplar's trace_id, renders through `obsctl
    requests` (table + waterfall), and exports as Perfetto trace events
    with one named track per replica."""
    import urllib.request

    from paddlepaddle_tpu.observability import exporter

    r = ServingRouter([_factory(FakeModel(fail_next=1)), _factory()],
                      probe_interval_s=60.0)
    try:
        r.submit(_prompt(), max_new_tokens=2).result(30)
    finally:
        r.stop()
    served = exporter.TelemetryExporter(port=0).start()
    try:
        doc = json.loads(urllib.request.urlopen(
            served.url("/requests"), timeout=5).read())
        assert doc["enabled"] and len(doc["journeys"]) == 1
        j = doc["journeys"][0]
        # exemplar -> journey join: the slowest TTFT's trace_id resolves
        ex = doc["exemplars"]["paddle_serving_ttft_seconds"]["slowest"]
        assert ex and ex[0]["trace_id"] == j["trace_id"]
        assert "le" in ex[0]
        # Perfetto export: a thread (track) metadata event per replica
        tr = json.loads(urllib.request.urlopen(
            served.url("/requests/trace"), timeout=5).read())
        tracks = {e["args"]["name"] for e in tr["traceEvents"]
                  if e.get("name") == "thread_name"}
        assert {"router", "r0", "r1"} <= tracks
        # obsctl: the table view and the single-journey waterfall
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "obsctl", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "tools", "obsctl.py"))
        obsctl = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(obsctl)
        target = f"127.0.0.1:{served.port}"
        assert obsctl.main(["requests", target]) == 0
        out = capsys.readouterr().out
        assert j["trace_id"] in out and "exemplars" in out
        assert obsctl.main(["requests", target, "--id",
                            j["trace_id"]]) == 0
        out = capsys.readouterr().out
        assert "router.attempt" in out and "breakdown:" in out
    finally:
        served.stop()


# -- SLO burn-rate gauges ----------------------------------------------------

def test_slo_burn_gauges_feed_health():
    """Armed targets produce sliding-window burn rates in every serving
    health() (engine AND router) plus the paddle_slo_burn_* gauges; with
    targets at 0 the block reports disabled and costs nothing."""
    import paddlepaddle_tpu.observability as obs

    reqtrace.reset()
    eng = _factory()()
    try:
        assert eng.health()["slo_burn"] == {"enabled": False}
        # an impossible TTFT target: every request violates; a huge TPOT
        # target: none does. budget 10% -> burn = rate / 0.1
        _flags.set_flags({"slo_ttft_ms": 1e-6, "slo_tpot_ms": 1e6,
                          "slo_error_budget": 0.1})
        for _ in range(5):
            eng.submit(_prompt(), max_new_tokens=2).result(30)
        burn = eng.health()["slo_burn"]
        assert burn["enabled"] and burn["ttft"]["requests"] == 5
        assert burn["ttft"]["violations"] == 5
        assert burn["ttft"]["burn"] == pytest.approx(10.0)
        # static mode streams nothing, so TPOT was never measured: the
        # block says so (no samples, burn None) instead of faking a zero
        assert burn["tpot"]["requests"] == 0
        assert burn["tpot"]["burn"] is None
        snap = obs.snapshot()
        assert snap["paddle_slo_burn_ttft"][()] == pytest.approx(10.0)
    finally:
        eng.stop()
        _flags.set_flags({"slo_ttft_ms": 0.0, "slo_tpot_ms": 0.0,
                          "slo_error_budget": 0.01})
        reqtrace.reset()
    # the router surfaces the same block
    r = ServingRouter([_factory()], probe_interval_s=60.0)
    try:
        assert r.health()["slo_burn"] == {"enabled": False}
    finally:
        r.stop()


def test_burn_window_slides():
    reqtrace.reset()
    _flags.set_flags({"slo_ttft_ms": 1.0, "slo_burn_window_s": 0.2})
    try:
        reqtrace.slo_observe(0.5, None)       # violation (500 ms > 1 ms)
        assert reqtrace.burn_snapshot()["ttft"]["violations"] == 1
        time.sleep(0.25)                      # sample ages out
        assert reqtrace.burn_snapshot()["ttft"]["requests"] == 0
    finally:
        _flags.set_flags({"slo_ttft_ms": 0.0, "slo_burn_window_s": 60.0})
        reqtrace.reset()


# -- flight recorder carries in-flight journeys ------------------------------

def test_flight_dump_carries_inflight_journeys(traced, tmp_path):
    from paddlepaddle_tpu.observability import flight

    flight.enable(str(tmp_path), capacity=64)
    try:
        j = traced.mint(7)
        j.event("admit", slot=0)
        path = flight.dump("test_crash")
        assert path is not None
        header = json.loads(open(path).readline())
        live = header["annotations"]["reqtrace_inflight"]
        assert any(row["trace_id"] == j.trace_id for row in live)
    finally:
        flight.disable()
