"""Unified runtime observability layer (observability/): span recorder,
metrics registry, hot-path instrumentation, recompilation watchdog, and the
single profiler/observability event pipeline.

Reference surface: paddle.profiler (host tracer + chrome-trace export),
paddle.monitor stat registries, per-collective comm logging.
"""

import json
import threading
import time

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.observability as obs
from paddlepaddle_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    exponential_buckets,
)
from paddlepaddle_tpu.observability.recorder import Recorder


@pytest.fixture
def clean_obs():
    """Observability fully off and empty before AND after each test — no
    instrumentation state may leak into other suites."""
    obs.disable()
    obs.reset()
    yield obs
    obs.disable()
    obs.reset()
    from paddlepaddle_tpu.observability import watchdog

    watchdog.set_storm_callback(None)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_aggregate():
    reg = Registry()
    c = reg.counter("c_total", "a counter")
    c.inc()
    c.inc(2, op="add")
    c.inc(3, op="add")
    assert c.value() == 1
    assert c.value(op="add") == 5
    g = reg.gauge("g", "a gauge")
    g.set(7)
    g.inc(2)
    g.dec()
    assert g.value() == 8
    # get-or-create is idempotent; kind conflicts are loud
    assert reg.counter("c_total") is c
    with pytest.raises(TypeError):
        reg.gauge("c_total")


def test_histogram_buckets_and_quantile():
    buckets = exponential_buckets(1e-3, 10.0, 4)  # 1ms,10ms,100ms,1s
    h = Histogram("h_seconds", buckets=buckets)
    for v in (5e-4, 5e-3, 5e-2, 5e-1, 5.0):
        h.observe(v)
    snap = h.snapshot()[()]
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(5.5555, rel=1e-3)
    assert snap["buckets"][1e-3] == 1      # 0.5ms
    assert snap["buckets"][float("inf")] == 1  # 5.0s overflows all bounds
    assert h.quantile(0.5) <= h.quantile(0.99)
    with pytest.raises(ValueError):
        exponential_buckets(0, 2, 3)
    edge = Histogram("edge", buckets=[1.0, 2.0])
    edge.observe(1.0)  # prometheus le (<=) semantics: ON the bound counts in
    assert edge.snapshot()[()]["buckets"][1.0] == 1


def test_prometheus_exposition(clean_obs):
    reg = Registry()
    reg.counter("paddle_x_total", "help text").inc(4, op="mul")
    reg.histogram("paddle_y_seconds", buckets=[0.1, 1.0]).observe(0.5)
    text = reg.to_prometheus_text()
    assert '# TYPE paddle_x_total counter' in text
    assert 'paddle_x_total{op="mul"} 4' in text
    assert '# TYPE paddle_y_seconds histogram' in text
    assert 'le="+Inf"' in text
    assert "paddle_y_seconds_sum" in text
    assert "paddle_y_seconds_count" in text


def test_prometheus_text_strict_round_trip(clean_obs):
    """Acceptance for exposition correctness: a strict parse of
    to_prometheus_text() over every registered family must see a HELP/TYPE
    pair, exact label round-trips (incl. escaping), and the histogram
    invariants — cumulative buckets, +Inf bucket == _count, _sum match."""
    from paddlepaddle_tpu.observability.metrics import parse_prometheus_text

    reg = Registry()
    c = reg.counter("paddle_rt_total", "a counter")
    c.inc(3, op="add")
    c.inc(2)  # unlabeled series alongside labeled ones
    # values past %g's 6 significant digits must round-trip exactly
    c.inc(123_456_789, op="big")
    g = reg.gauge("paddle_rt_depth", "a gauge")
    # label escaping: backslash, quote, newline must survive the round trip
    nasty = 'sl\\ash "quoted"\nline'
    g.set(7.5, which=nasty)
    h = reg.histogram("paddle_rt_seconds", "a histogram",
                      buckets=[0.001, 0.01, 0.1, 1.0])
    for v in (0.0005, 0.005, 0.005, 0.05, 0.5, 5.0):
        h.observe(v, op="step")

    fams = parse_prometheus_text(reg.to_prometheus_text())
    # every registered family appears exactly once, with HELP and TYPE
    assert set(fams) == {"paddle_rt_total", "paddle_rt_depth",
                         "paddle_rt_seconds"}
    for name, fam in fams.items():
        assert fam["type"] in ("counter", "gauge", "histogram")
        assert fam["help"], f"{name} lost its HELP text"

    counter_rows = {tuple(sorted(lab.items())): v
                    for _, lab, v in fams["paddle_rt_total"]["samples"]}
    assert counter_rows == {(("op", "add"),): 3.0, (): 2.0,
                            (("op", "big"),): 123_456_789.0}

    (_, lab, v), = fams["paddle_rt_depth"]["samples"]
    assert lab == {"which": nasty}  # escaping round-tripped exactly
    assert v == 7.5

    hs = fams["paddle_rt_seconds"]["samples"]
    buckets = [(lab["le"], v) for n, lab, v in hs
               if n == "paddle_rt_seconds_bucket"]
    counts = [v for _, v in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert [le for le, _ in buckets][-1] == "+Inf"
    (count,) = [v for n, _, v in hs if n == "paddle_rt_seconds_count"]
    (total,) = [v for n, _, v in hs if n == "paddle_rt_seconds_sum"]
    assert counts[-1] == count == 6  # +Inf bucket equals _count
    assert total == pytest.approx(0.0005 + 0.005 + 0.005 + 0.05 + 0.5 + 5.0,
                                  rel=1e-6)

    # strictness: samples without a declared family are an error, as is an
    # unknown type
    with pytest.raises(ValueError, match="no declared"):
        parse_prometheus_text("paddle_orphan_total 1\n")
    with pytest.raises(ValueError, match="unknown type"):
        parse_prometheus_text("# HELP x h\n# TYPE x summary\nx 1\n")


def test_prometheus_round_trip_every_registered_family(clean_obs):
    """Drive the REAL hot-path instrumentation, then round-trip the entire
    global registry — every family the framework registers must satisfy
    the same invariants (this is what the /metrics endpoint serves)."""
    from paddlepaddle_tpu.observability.metrics import parse_prometheus_text

    obs.enable(trace=False, metrics=True, watchdog_=False)
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    for _ in range(3):
        _ = paddle.add(x, x)
    obs.safe_inc("paddle_rt_fault_total", "fault probe", reason="test")
    obs.disable()

    reg = obs.get_registry()
    fams = parse_prometheus_text(obs.to_prometheus_text())
    assert set(fams) == set(reg.names())
    assert fams["paddle_op_seconds"]["samples"]  # the driven histogram
    for name, fam in fams.items():
        m = reg.get(name)
        assert fam["type"] == m.kind
        if m.kind != "histogram":
            continue
        # histogram invariants per label set: cumulative buckets ending at
        # +Inf == _count, and _sum consistent with the live snapshot
        by_labels = {}
        for sample_name, lab, v in fam["samples"]:
            key = tuple(sorted((k, val) for k, val in lab.items()
                               if k != "le"))
            row = by_labels.setdefault(key, {"buckets": [], "sum": None,
                                             "count": None})
            if sample_name.endswith("_bucket"):
                row["buckets"].append((lab["le"], v))
            elif sample_name.endswith("_sum"):
                row["sum"] = v
            elif sample_name.endswith("_count"):
                row["count"] = v
        if not by_labels:
            continue  # registered but never observed: exposes nothing
        for key, row in by_labels.items():
            counts = [v for _, v in row["buckets"]]
            assert counts == sorted(counts), (name, key)
            assert row["buckets"][-1][0] == "+Inf"
            assert counts[-1] == row["count"], (name, key)
            snap = m.snapshot()[key]
            assert row["sum"] == pytest.approx(snap["sum"], rel=1e-6,
                                               abs=1e-12)
            assert row["count"] == snap["count"]


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------

def test_spans_nest_across_threads(clean_obs):
    obs.enable(trace=True, metrics=False, watchdog_=False)
    barrier = threading.Barrier(2)

    def worker(tag):
        with obs.RecordEvent(f"outer_{tag}"):
            barrier.wait()  # both outers open before any inner opens
            with obs.RecordEvent(f"inner_{tag}"):
                time.sleep(0.005)

    threads = [threading.Thread(target=worker, args=(t,)) for t in "ab"]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    evs = {e.name: e for e in obs.get_recorder().events()}
    tids = set()
    for tag in "ab":
        outer, inner = evs[f"outer_{tag}"], evs[f"inner_{tag}"]
        # per-thread stacks: inner nested inside ITS OWN thread's outer
        assert inner.tid == outer.tid
        assert outer.ts_us <= inner.ts_us
        assert inner.ts_us + inner.dur_us <= outer.ts_us + outer.dur_us + 1
        tids.add(outer.tid)
    assert len(tids) == 2  # interleaving really happened on two threads


def test_ring_buffer_bounded():
    rec = Recorder(capacity=10)
    for i in range(25):
        rec.record_complete(f"e{i}", "t", 0.0)
    evs = rec.events()
    assert len(evs) == 10
    assert evs[0].name == "e15"  # oldest fell off
    assert rec.stats()["e3"][0] == 1  # aggregates survive eviction


def test_chrome_trace_export_valid_json(tmp_path, clean_obs):
    obs.enable(trace=True, metrics=False, watchdog_=False)
    with obs.RecordEvent("step"):
        with obs.RecordEvent("forward"):
            pass
    path = str(tmp_path / "trace.json")
    obs.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)  # must be VALID json — Perfetto's loader
    assert doc["displayTimeUnit"] == "ms"
    names = [e["name"] for e in doc["traceEvents"]]
    assert "step" in names and "forward" in names
    for e in doc["traceEvents"]:
        assert e["ph"] == "X"
        for key in ("ts", "dur", "pid", "tid"):
            assert isinstance(e[key], int)


def test_trace_region_decorator(clean_obs):
    calls = []

    @obs.trace_region("decorated_fn", force=True)
    def fn(x):
        calls.append(x)
        return x + 1

    assert fn(1) == 2
    assert calls == [1]
    assert "decorated_fn" in obs.get_recorder().stats()


# ---------------------------------------------------------------------------
# hot-path instrumentation
# ---------------------------------------------------------------------------

def test_dispatch_records_op_exactly_once_per_call(clean_obs):
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    y = paddle.to_tensor(np.ones((2, 2), np.float32))
    obs.enable(trace=False, metrics=True, watchdog_=False)
    for _ in range(3):
        _ = paddle.add(x, y)
    obs.disable()
    _ = paddle.add(x, y)  # after disable: not counted
    snap = obs.snapshot()
    counts = {dict(k).get("op"): v
              for k, v in snap["paddle_op_calls_total"].items()}
    assert counts["add"] == 3
    lat = snap["paddle_op_seconds"]
    add_key = (("op", "add"),)
    assert lat[add_key]["count"] == 3
    assert lat[add_key]["sum"] > 0


def test_train_loop_summary_shows_dispatch_autograd_collective(clean_obs):
    """Acceptance: summary() after a 3-step train loop shows per-op
    counts/timings for dispatch, autograd, and at least one collective."""
    lin = paddle.nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    obs.enable(trace=True, metrics=True, watchdog_=False)
    for _ in range(3):
        loss = ((lin(x) - 1.0) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        grad_like = paddle.to_tensor(np.ones((4,), np.float32))
        paddle.distributed.all_reduce(grad_like)
    out = obs.summary()
    obs.disable()
    assert "Dispatch (eager ops)" in out
    assert "linear" in out and "mean" in out
    assert "Autograd (grad nodes)" in out
    assert "Collectives (eager)" in out
    assert "all_reduce" in out

    snap = obs.snapshot()
    cap = sum(snap["paddle_autograd_nodes_captured_total"].values())
    ex = sum(snap["paddle_autograd_nodes_executed_total"].values())
    assert cap > 0 and ex > 0
    coll = {dict(k).get("coll"): v
            for k, v in snap["paddle_collective_calls_total"].items()}
    assert coll["all_reduce"] == 3
    byts = {dict(k).get("coll"): v
            for k, v in snap["paddle_collective_bytes_total"].items()}
    assert byts["all_reduce"] == 3 * 4 * 4  # 3 calls x 4 float32


def test_comm_task_latency_recorded(clean_obs):
    from paddlepaddle_tpu.distributed.comm_task import comm_task

    obs.enable(trace=False, metrics=True, watchdog_=False)
    with comm_task("fake_all_gather", group="tp"):
        time.sleep(0.002)
    obs.disable()
    snap = obs.snapshot()["paddle_comm_task_seconds"]
    key = (("group", "tp"), ("task", "fake_all_gather"))
    assert snap[key]["count"] == 1
    assert snap[key]["sum"] >= 0.002


def test_dataloader_batches_counted(clean_obs):
    from paddlepaddle_tpu.io import DataLoader
    from paddlepaddle_tpu.io.dataset import Dataset

    class _DS(Dataset):
        def __getitem__(self, i):
            return np.full((2,), i, np.float32)

        def __len__(self):
            return 8

    obs.enable(trace=False, metrics=True, watchdog_=False)
    loader = DataLoader(_DS(), batch_size=2, num_workers=0)
    n = sum(1 for _ in loader)
    obs.disable()
    assert n == 4
    snap = obs.snapshot()
    assert snap["paddle_dataloader_batches_total"][()] == 4


def test_serving_future_latency_recorded(clean_obs):
    serving = pytest.importorskip("paddlepaddle_tpu.inference.serving")
    obs.enable(trace=False, metrics=True, watchdog_=False)
    r = serving.GenerationResult()
    time.sleep(0.002)
    r._set(output=np.zeros(1))
    bad = serving.GenerationResult()
    bad._set(error=RuntimeError("boom"))
    obs.disable()
    snap = obs.snapshot()
    lat = snap["paddle_serving_request_seconds"][()]
    assert lat["count"] == 1 and lat["sum"] >= 0.002
    reqs = {dict(k).get("outcome"): v
            for k, v in snap["paddle_serving_requests_total"].items()}
    assert reqs == {"ok": 1, "error": 1}


# ---------------------------------------------------------------------------
# one event pipeline: paddle.profiler rides the observability recorder
# ---------------------------------------------------------------------------

def test_profiler_record_event_single_pipeline(tmp_path, clean_obs):
    from paddlepaddle_tpu.profiler import Profiler, RecordEvent

    prof = Profiler(timer_only=True).start()
    with RecordEvent("shared_region"):
        _ = paddle.to_tensor(np.ones((2, 2), np.float32)) * 2
    prof.step()
    prof.stop()
    # the SAME span is visible through both read APIs
    assert "shared_region" in prof.summary()
    assert "shared_region" in obs.get_recorder().stats("record_event")
    path = prof.export(str(tmp_path / "host.json"))
    with open(path) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "shared_region" in names
    # explicit path records WITHOUT any PADDLE_OBS flags enabled
    assert not obs.is_enabled()


# ---------------------------------------------------------------------------
# recompilation watchdog
# ---------------------------------------------------------------------------

def test_recompile_watchdog_fires_on_shape_polymorphic_jit(clean_obs):
    import jax
    import jax.numpy as jnp

    from paddlepaddle_tpu.observability import watchdog

    storms = []
    watchdog.set_storm_callback(lambda site, n: storms.append((site, n)))
    paddle.set_flags({"FLAGS_obs_recompile_threshold": 3})
    obs.enable(trace=False, metrics=True, watchdog_=True)
    try:
        f = jax.jit(lambda x: x * 2 + 1)
        for n in (17, 18, 19, 20):  # shape-polymorphic: a compile per call
            f(jnp.ones((n,))).block_until_ready()
    finally:
        obs.disable()
        paddle.set_flags({"FLAGS_obs_recompile_threshold": 3})
    counts = watchdog.compile_counts()
    assert sum(counts.values()) >= 4
    # attribution: the offending callsite is THIS test, not jax internals
    assert any(__file__ in site for site in counts)
    assert storms and storms[0][1] >= 3
    assert "storm" in watchdog.report()
    # compiles also land in the metrics registry
    snap = obs.snapshot()
    assert sum(snap["paddle_jit_compiles_total"].values()) >= 4


def test_watchdog_quiet_for_stable_signature(clean_obs):
    import jax
    import jax.numpy as jnp

    from paddlepaddle_tpu.observability import watchdog

    storms = []
    watchdog.set_storm_callback(lambda site, n: storms.append(site))
    x = jnp.ones((23,))  # materialize BEFORE watching (jnp.ones compiles too)
    obs.enable(trace=False, metrics=False, watchdog_=True)
    try:
        f = jax.jit(lambda x: x + 1)
        for _ in range(5):  # one compile, four cache hits
            f(x).block_until_ready()
    finally:
        obs.disable()
    assert not storms
    assert sum(watchdog.compile_counts().values()) <= 1


# ---------------------------------------------------------------------------
# flags / env plumbing and off-overhead
# ---------------------------------------------------------------------------

def test_summary_carries_rank_world_header(clean_obs, monkeypatch):
    """A summary pasted from a multi-host job must say which worker it came
    from (rank/world from distributed/env.py, host, pid)."""
    import os

    out = obs.summary()
    assert "rank 0/1" in out.splitlines()[1]
    assert f"pid {os.getpid()}" in out
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "8")
    assert "rank 3/8" in obs.summary().splitlines()[1]


def test_obs_flags_read_padle_obs_env(monkeypatch):
    from paddlepaddle_tpu.core import flags as flags_mod

    monkeypatch.setenv("PADDLE_OBS_TEST_PROBE", "1")
    f = flags_mod.define_flag("obs_test_probe", False,
                              env="PADDLE_OBS_TEST_PROBE")
    assert f.value is True
    assert flags_mod.flag_value("obs_test_probe") is True


def test_optional_module_placeholder_error():
    missing = paddle._optional_import("definitely_not_a_module_xyz")
    assert "unavailable" in repr(missing)
    with pytest.raises(ImportError, match="definitely_not_a_module_xyz"):
        missing.anything


def test_disabled_overhead_under_5pct_on_10k_op_microloop(clean_obs):
    """With PADDLE_OBS_* off the dispatch hot path pays one module-global
    read + branch. Compare the instrumented entry (apply_op) against the
    uninstrumented inner (_apply_op) over a 10k-op microloop."""
    import jax.numpy as jnp

    from paddlepaddle_tpu.core import dispatch

    assert dispatch._obs_op is None  # flags off: no hook installed
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    y = paddle.to_tensor(np.ones((2, 2), np.float32))
    N = 10_000

    def loop_entry():
        t0 = time.perf_counter()
        for _ in range(N):
            dispatch.apply_op(jnp.add, x, y, op_name="add")
        return time.perf_counter() - t0

    def loop_bare():
        # inner's positional convention; the (x, y) tuple literal mirrors
        # the *args pack the entry call pays
        t0 = time.perf_counter()
        for _ in range(N):
            dispatch._apply_op(jnp.add, (x, y), {}, "add", None)
        return time.perf_counter() - t0

    import gc
    import statistics

    def measure():
        """Median of per-round PAIRED ratios: drift (frequency scaling,
        background load on a shared box) cancels within a round, the
        median discards outlier rounds."""
        ratios = []
        gc.disable()
        try:
            for _ in range(7):
                ratios.append(loop_entry() / loop_bare())
        finally:
            gc.enable()
        return statistics.median(ratios) - 1.0

    loop_entry()  # warmup both paths (jit/caches)
    loop_bare()
    overhead = measure()
    if overhead >= 0.05:  # one retry: a noise spike must not fail CI, a
        overhead = measure()  # real regression fails both rounds
    assert overhead < 0.05, (
        f"disabled-instrumentation overhead {overhead:.1%} on {N}-op "
        f"microloop (median of paired rounds, after retry)")


def test_enable_disable_roundtrip_installs_and_clears_hooks(clean_obs):
    from paddlepaddle_tpu.core import autograd as ag
    from paddlepaddle_tpu.core import dispatch
    from paddlepaddle_tpu.distributed import collective, comm_task
    from paddlepaddle_tpu.io import dataloader

    obs.enable(trace=True, metrics=True, watchdog_=False)
    assert dispatch._obs_op is not None
    assert ag._obs_node is not None
    assert collective._obs_coll is not None
    assert comm_task._obs_task is not None
    assert dataloader._obs_io is not None
    assert obs.is_enabled()
    obs.disable()
    assert dispatch._obs_op is None
    assert ag._obs_node is None
    assert collective._obs_coll is None
    assert comm_task._obs_task is None
    assert dataloader._obs_io is None
    assert not obs.is_enabled()
