"""Elastic fleet control plane: SLO-driven autoscaling (hysteresis,
cooldowns, bounds), dynamic router membership with bounded rendezvous key
movement, scale-down cleanup (no /healthz provider leaks, no stale breaker
evidence), and the zero-downtime deploy pipeline with automatic rollback
(inference/fleet.py + router.py add/remove/restart_replica).

Fast tests drive fleets of STATIC fake-model engines (the test_router.py
pattern) so the control plane is exercised without JAX compiles; the
real-engine 4x-traffic-step-during-rollout drill with an injected
preemption runs behind the chaos/slow markers (tools/run_chaos.sh). The
invariants: every submitted future resolves completed-or-typed, a scale
decision needs a SUSTAINED signal, and a failed deploy always ends with
every replica serving the previous version.
"""

import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from paddlepaddle_tpu.inference import (
    DeployError,
    FleetController,
    FleetPolicy,
    ServingEngine,
    ServingError,
    ServingRouter,
)
from paddlepaddle_tpu.inference.fleet import decide
from test_serving_robustness import FakeModel, _prompt

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_QUIET = 60.0     # prober quiet; tests drive probes/ticks explicitly


def _policy(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_streak", 2)
    kw.setdefault("down_streak", 3)
    kw.setdefault("cooldown_up_s", 0.0)
    kw.setdefault("cooldown_down_s", 0.0)
    kw.setdefault("health_timeout_s", 5.0)
    kw.setdefault("drain_timeout_s", 2.0)
    return FleetPolicy(**kw)


def _factory(model_fn=None):
    """Versioned engine factory over instant fake models; ``model_fn``
    maps the version label to a model (the deploy tests' seam)."""

    def factory(version):
        model = model_fn(version) if model_fn is not None else FakeModel()
        return ServingEngine(model, mode="static", max_batch_size=4,
                             max_wait_ms=2.0, max_len=64)

    return factory


def _fleet(n=1, model_fn=None, policy=None, **kw):
    fc = FleetController(_factory(model_fn), initial_replicas=n,
                         policy=policy or _policy(),
                         probe_interval_s=_QUIET, **kw)
    fc.start(autoscaler=False)
    fc.router._probe_once()
    return fc


def _force_signal(fc, est_wait, queue_depth=0):
    for rep in fc.router._replicas:
        rep.snapshot = dict(rep.snapshot or {}, ok=True,
                            est_wait_s=est_wait, queue_depth=queue_depth)


def _mk_bundle(tmp, name, corrupt=False):
    """A manifest-only candidate bundle: enough for the deploy pipeline's
    stdlib validation (real AOT payload round-trips are pinned by
    tests/test_compile_plan.py in fresh subprocesses)."""
    bp = os.path.join(str(tmp), name)
    os.makedirs(bp, exist_ok=True)
    manifest = {"format_version": 1, "created_unix": time.time(),
                "version": f"{name}-vid", "fingerprint": "f" * 64,
                "entries": []}
    if corrupt:
        with open(os.path.join(bp, "decode.xc"), "wb") as f:
            f.write(b"junk")
        manifest["entries"] = [{"key": "decode", "file": "decode.xc",
                                "bytes": 4, "sha256": "0" * 64}]
    with open(os.path.join(bp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    return bp


def _resolve_all(futs, timeout=60):
    oks, errs = [], []
    for f in futs:
        try:
            oks.append(f.result(timeout))
        except Exception as e:  # noqa: BLE001 — collected for assertions
            errs.append(e)
    return oks, errs


# -- policy ------------------------------------------------------------------

def test_decide_hysteresis_cooldowns_and_bounds():
    pol = _policy(min_replicas=1, max_replicas=3, up_streak=2,
                  down_streak=3, cooldown_up_s=10.0, cooldown_down_s=20.0)
    state = {"hot": 0, "idle": 0, "last_action_t": None}
    hot = {"replicas": 1, "healthy": 1, "est_wait_max": 5.0,
           "queue_depth": 4, "burn": None}
    # one hot reading is NOT a decision (hysteresis)
    action, reason = decide(pol, hot, state, now=100.0)
    assert action is None and "streak 1/2" in reason
    action, reason = decide(pol, hot, state, now=101.0)
    assert action == "up" and "est_wait" in reason
    # burn beats est-wait as the named reason
    burn_sig = dict(hot, est_wait_max=0.0, burn=3.0)
    state2 = {"hot": 1, "idle": 0, "last_action_t": None}
    action, reason = decide(pol, burn_sig, state2, now=0.0)
    assert action == "up" and "slo_burn" in reason
    # cooldown blocks, streak keeps accumulating
    state3 = {"hot": 5, "idle": 0, "last_action_t": 99.0}
    action, reason = decide(pol, hot, state3, now=100.0)
    assert action is None and "cooldown" in reason
    action, _ = decide(pol, hot, state3, now=200.0)
    assert action == "up"
    # max bound refuses even a sustained violation
    at_max = dict(hot, replicas=3)
    action, reason = decide(pol, at_max, {"hot": 9, "idle": 0,
                                          "last_action_t": None}, 0.0)
    assert action is None and "max_replicas" in reason
    # idle needs its own (longer) streak, then scales down
    idle = {"replicas": 2, "healthy": 2, "est_wait_max": 0.0,
            "queue_depth": 0, "burn": 0.0}
    state4 = {"hot": 0, "idle": 0, "last_action_t": None}
    for i in range(2):
        action, _ = decide(pol, idle, state4, now=float(i))
        assert action is None
    action, reason = decide(pol, idle, state4, now=3.0)
    assert action == "down" and "idle" in reason
    # min bound refuses
    at_min = dict(idle, replicas=1)
    action, reason = decide(pol, at_min, {"hot": 0, "idle": 9,
                                          "last_action_t": None}, 0.0)
    assert action is None and "min_replicas" in reason
    # a queue that is backed up but not over the est-wait bound resets
    # BOTH streaks (neither hot nor idle)
    mid = {"replicas": 2, "healthy": 2, "est_wait_max": 0.5,
           "queue_depth": 3, "burn": None}
    state5 = {"hot": 1, "idle": 2, "last_action_t": None}
    action, reason = decide(pol, mid, state5, now=0.0)
    assert action is None and state5["hot"] == 0 and state5["idle"] == 0


# -- router membership -------------------------------------------------------

def test_add_remove_replica_bounded_rendezvous_movement():
    """Joining a replica moves ONLY the prefix keys it now owns; leaving
    returns exactly those keys to their previous homes — the property
    that keeps the fleet-wide prompt-cache hit rate through scaling."""
    r = ServingRouter([lambda: ServingEngine(FakeModel(), mode="static",
                                             max_batch_size=4, max_len=64)
                       for _ in range(3)], probe_interval_s=_QUIET)
    r.start()
    try:
        r._probe_once()
        rng = np.random.default_rng(0)
        prefixes = [rng.integers(0, 1000, (16,)).astype(np.int32)
                    for _ in range(24)]

        def route(p):
            class _P:
                tried = set()
                prefix_key = p.tobytes()

            return r._pick(_P()).name

        before = {p.tobytes(): route(p) for p in prefixes}
        name = r.add_replica(lambda: ServingEngine(
            FakeModel(), mode="static", max_batch_size=4, max_len=64))
        r._probe_once()
        assert name == "r3" and len(r._replicas) == 4
        after = {p.tobytes(): route(p) for p in prefixes}
        moved = {k for k in before if after[k] != before[k]}
        assert all(after[k] == "r3" for k in moved), \
            "keys may move ONLY onto the joining replica"
        assert moved, "24 prefixes over 4 replicas should give r3 some keys"
        # the new replica actually serves routed traffic
        assert r.submit(_prompt(), max_new_tokens=2).result(30).shape == (6,)
        # duplicate names are refused
        with pytest.raises(ValueError):
            r.add_replica(lambda: ServingEngine(
                FakeModel(), mode="static", max_batch_size=4, max_len=64),
                name="r1")
        # leaving: exactly the owned keys return to their old homes
        res = r.remove_replica("r3")
        assert res["replica"] == "r3" and len(r._replicas) == 3
        restored = {p.tobytes(): route(p) for p in prefixes}
        assert restored == before
        assert r.stats["replicas_added"] == 1
        assert r.stats["replicas_removed"] == 1
    finally:
        r.stop()


def test_remove_replica_is_deliberate_and_refuses_last():
    r = ServingRouter([lambda: ServingEngine(
        FakeModel(delay_s=0.02), mode="static", max_batch_size=1,
        max_len=64) for _ in range(2)], probe_interval_s=_QUIET)
    try:
        futs = [r.submit(_prompt(), max_new_tokens=2) for _ in range(6)]
        res = r.remove_replica("r1", drain_timeout=5.0)
        oks, errs = _resolve_all(futs)
        # zero dropped: drain sheds failed over to the surviving replica
        assert len(oks) == 6 and not errs, \
            [f"{type(e).__name__}: {e}" for e in errs]
        assert res["clean"] is True
        # deliberate: no eviction was recorded, no breaker opened
        assert r.stats["evictions"] == 0
        assert res["breaker"] == "closed"
        # the removed engine is really stopped (its loop thread is gone)
        assert "r1" not in [rep.name for rep in r._replicas]
        with pytest.raises(ValueError):
            r.remove_replica("r0")
        with pytest.raises(KeyError):
            r.remove_replica("r7")
    finally:
        r.stop()


# -- autoscaler --------------------------------------------------------------

def test_scale_up_on_sustained_violation_with_cooldown_and_max():
    pol = _policy(max_replicas=3, up_streak=2, cooldown_up_s=30.0)
    fc = _fleet(1, policy=pol)
    try:
        _force_signal(fc, est_wait=5.0)
        assert fc._tick()["action"] is None        # streak 1: hysteresis
        assert len(fc.router._replicas) == 1
        assert fc._tick()["action"] == "up"        # streak 2: scale
        assert len(fc.router._replicas) == 2
        assert fc.stats["scale_ups"] == 1
        assert fc.last_scaleup_to_healthy_s is not None
        assert fc.health()["fleet"]["replicas_target"] == 2
        # the new replica serves routed traffic immediately (pre-warmed +
        # probed before it entered the pick set)
        fc.router._probe_once()
        assert fc.generate(_prompt(), max_new_tokens=2,
                           timeout=30).shape == (6,)
        # cooldown: the violation persists but no second scale fires
        _force_signal(fc, est_wait=5.0)
        for _ in range(4):
            fc._tick()
        assert len(fc.router._replicas) == 2
        # cooldown elapsed (rewound, not slept) -> next sustained
        # violation adds the third; max_replicas then caps the fleet
        fc._state["last_action_t"] -= 60.0
        _force_signal(fc, est_wait=5.0)
        for _ in range(3):
            fc._tick()
        assert len(fc.router._replicas) == 3
        fc._state["last_action_t"] -= 60.0
        _force_signal(fc, est_wait=5.0)
        for _ in range(3):
            assert fc._tick()["action"] is None
        assert len(fc.router._replicas) == 3      # hard max bound
    finally:
        fc.stop()


def test_scale_down_idle_by_deliberate_drain():
    pol = _policy(min_replicas=1, down_streak=3, cooldown_down_s=0.0)
    fc = _fleet(3, policy=pol)
    try:
        _force_signal(fc, est_wait=0.0, queue_depth=0)
        for _ in range(2):
            assert fc._tick()["action"] is None
        assert fc._tick()["action"] == "down"
        assert len(fc.router._replicas) == 2
        assert fc.stats["scale_downs"] == 1
        # deliberate: the drain produced no breaker/eviction evidence
        assert fc.router.stats["evictions"] == 0
        # down to min, then the bound holds
        _force_signal(fc, est_wait=0.0)
        for _ in range(3):
            fc._tick()
        assert len(fc.router._replicas) == 1
        _force_signal(fc, est_wait=0.0)
        for _ in range(4):
            assert fc._tick()["action"] is None
        assert len(fc.router._replicas) == 1      # hard min bound
        fc.router._probe_once()
        assert fc.generate(_prompt(), max_new_tokens=2,
                           timeout=30).shape == (6,)
    finally:
        fc.stop()


def test_scale_down_bounds_in_rotation_capacity_not_census():
    """min_replicas bounds SERVING capacity: with a deploy's canary out
    of rotation, an idle streak must not drain the replica actually
    carrying the traffic (found by an e2e drive where a mid-deploy
    scale-down left the fleet with zero in-rotation replicas)."""
    fc = _fleet(2, policy=_policy(min_replicas=1, down_streak=1))
    try:
        fc.router._replicas[0].in_rotation = False   # canary out
        _force_signal(fc, est_wait=0.0, queue_depth=0)
        for _ in range(3):
            assert fc._tick()["action"] != "down" or \
                len(fc.router._replicas) == 2
        assert len(fc.router._replicas) == 2
        assert fc.stats["scale_downs"] == 0
        # canary readmitted -> the idle streak may drain again
        fc.router._replicas[0].in_rotation = True
        _force_signal(fc, est_wait=0.0, queue_depth=0)
        fc._tick()
        assert len(fc.router._replicas) == 1
    finally:
        fc.stop()


def test_scale_cycle_no_provider_leaks_no_stale_breaker():
    """The satellite fix pin: scale-up -> scale-down -> scale-up leaves no
    orphaned /healthz provider and no stale breaker evidence — a removed
    replica's engine unregisters itself, and the router drops its breaker
    with it, so a later replica starts with a clean slate."""
    from paddlepaddle_tpu.observability import exporter as _exporter

    e = _exporter.start(port=0)
    fc = None
    try:
        fc = _fleet(1, policy=_policy(max_replicas=3))
        baseline = len(e._health_providers)   # router + fleet + 1 serving
        serving_n = sum(1 for n in e._health_providers if "serving" in n)
        assert serving_n == 1
        for cycle in range(2):
            _force_signal(fc, est_wait=5.0)
            for _ in range(2):
                fc._tick()
            assert len(fc.router._replicas) == 2
            assert sum(1 for n in e._health_providers
                       if "serving" in n) == 2
            # poison the breaker history of the replica scale-down will
            # pick (least loaded, name-ordered tiebreak): its evidence
            # must leave WITH it
            victim = min(fc.router._replicas,
                         key=lambda r: (r.inflight, r.name))
            victim.breaker.record_failure()
            victim.breaker.record_failure()
            _force_signal(fc, est_wait=0.0)
            for _ in range(3):
                fc._tick()
            assert len(fc.router._replicas) == 1, f"cycle {cycle}"
            # no provider leak: the removed engine unregistered itself
            assert len(e._health_providers) == baseline, \
                sorted(e._health_providers)
        # every surviving replica's breaker is clean (no stale evidence
        # from any removed replica's poisoned history)
        for rep in fc.router._replicas:
            assert rep.breaker.consecutive_failures == 0
            assert rep.breaker.state == "closed"
        fc.router._probe_once()
        assert fc.generate(_prompt(), max_new_tokens=2,
                           timeout=30).shape == (6,)
    finally:
        if fc is not None:
            fc.stop()
        _exporter.stop()


def test_autoscaler_thread_closes_the_loop():
    """The loop form: a sustained synthetic violation scales the fleet
    without anyone calling _tick()."""
    pol = _policy(max_replicas=2, up_streak=2)
    pol.interval_s = 0.02
    fc = FleetController(_factory(), initial_replicas=1, policy=pol,
                         probe_interval_s=_QUIET)
    fc.start()                  # autoscaler thread on
    try:
        fc.router._probe_once()
        deadline = time.time() + 5.0
        while time.time() < deadline and len(fc.router._replicas) < 2:
            _force_signal(fc, est_wait=5.0)   # keep the signal hot (new
            time.sleep(0.02)                  # replicas join idle)
        assert len(fc.router._replicas) == 2
        assert fc.health()["fleet"]["autoscaler"]["running"]
    finally:
        fc.stop()
    assert not fc.health()["fleet"]["autoscaler"]["running"]


# -- deploy pipeline ---------------------------------------------------------

def test_deploy_promotes_under_traffic_with_zero_drops(tmp_path):
    v2 = _mk_bundle(tmp_path, "v2")
    fc = _fleet(3, policy=_policy(), retry_policy=None)
    futs, stop = [], threading.Event()
    lock = threading.Lock()

    def client():
        while not stop.is_set():
            try:
                f = fc.submit(_prompt(), max_new_tokens=2)
            except ServingError:
                continue
            with lock:
                futs.append(f)
            time.sleep(0.002)

    threads = [threading.Thread(target=client) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.05)
        res = fc.deploy(v2, canary_requests=3, canary_new_tokens=2)
        stop.set()
        for t in threads:
            t.join(30)
        assert res["ok"], res
        assert res["stage"] == "done"
        assert res["version"] == v2 and res["previous"] is None
        assert res["manifest_version"] == "v2-vid"
        assert res["canary"]["completed"] == 3
        # every replica serves the candidate, through a fresh engine
        assert set(fc._versions.values()) == {v2}
        assert all(rep.client.generation >= 1
                   for rep in fc.router._replicas)
        assert fc.version == v2 and fc.previous_version is None
        assert fc.rollout["state"] == "done"
        assert fc.stats["rollouts"] == 1 and fc.stats["rollbacks"] == 0
        # zero dropped requests across the whole rollout
        with lock:
            taken = list(futs)
        assert len(taken) > 10
        oks, errs = _resolve_all(taken)
        assert not errs, [f"{type(e).__name__}: {e}" for e in errs[:5]]
        fc.router._probe_once()
        h = fc.health()
        assert h["ok"] and h["fleet"]["version"] == v2
    finally:
        stop.set()
        for t in threads:
            t.join(5)
        fc.stop()


def test_deploy_rejects_bad_bundles_before_touching_the_fleet(tmp_path):
    fc = _fleet(2)
    try:
        gens = [rep.client.generation for rep in fc.router._replicas]
        with pytest.raises(DeployError) as ei:
            fc.deploy(os.path.join(str(tmp_path), "missing"))
        assert ei.value.stage == "validate"
        corrupt = _mk_bundle(tmp_path, "bad", corrupt=True)
        with pytest.raises(DeployError) as ei:
            fc.deploy(corrupt)
        assert ei.value.stage == "validate"
        assert "sha256" in str(ei.value)
        # the fleet was never touched: no restarts, no version change
        assert [rep.client.generation
                for rep in fc.router._replicas] == gens
        assert fc.version is None and fc.rollout["state"] == "idle"
        assert isinstance(ei.value, ServingError)
    finally:
        fc.stop()


def test_deploy_canary_gate_failure_rolls_back(tmp_path):
    """A candidate whose canary requests fail never reaches a second
    replica; the canary is restored to the previous version and the
    fleet keeps serving."""
    bad = _mk_bundle(tmp_path, "bad")

    def model_fn(version):
        return FakeModel(fail_next=10 ** 6) if version == bad \
            else FakeModel()

    fc = _fleet(2, model_fn=model_fn)
    try:
        res = fc.deploy(bad, canary_requests=2, canary_new_tokens=2,
                        canary_timeout=30)
        assert not res["ok"] and res["stage"] == "canary"
        assert "canary requests failed" in res["reasons"][0]
        # rolled back: everyone on the previous version, fleet healthy
        assert set(fc._versions.values()) == {None}
        assert fc.version is None
        assert fc.rollout["state"] == "rolled_back"
        assert fc.rollout["reasons"] == res["reasons"]
        assert fc.stats["rollbacks"] == 1 and fc.stats["rollouts"] == 0
        fc.router._probe_once()
        assert fc.health()["ok"]
        oks, errs = _resolve_all(
            [fc.submit(_prompt(), max_new_tokens=2) for _ in range(4)])
        assert len(oks) == 4 and not errs
        # a canary that never turns HEALTHY rolls back the same way
        # (health-gate failure, not probe failure). A tripped breaker is
        # the persistent not-ok state: start() deliberately does NOT
        # clear it (only the drain->start cycle resets failure history)
        dead = _mk_bundle(tmp_path, "dead")
        orig = fc.factory

        def factory(version):
            eng = orig(version)
            if version == dead:
                eng._breaker.trip()
            return eng

        fc.factory = factory
        fc.policy.health_timeout_s = 0.4
        res = fc.deploy(dead, canary_requests=1)
        assert not res["ok"] and res["stage"] == "canary"
        assert "never turned healthy" in res["reasons"][0]
        assert set(fc._versions.values()) == {None}
        fc.router._probe_once()
        assert fc.health()["ok"]
    finally:
        fc.stop()


def test_deploy_midrollout_regression_rolls_back_every_replica(tmp_path):
    """The acceptance pin: the canary passes, then a LATER replica fails
    its health gate on the candidate mid-rollout — the pipeline
    automatically restores the previous bundle on every updated replica
    (canary included) and the fleet ends the rollout serving the previous
    version everywhere."""
    v2 = _mk_bundle(tmp_path, "v2")
    builds = {"n": 0}

    def model_fn(version):
        return FakeModel()

    fc = _fleet(3, model_fn=model_fn,
                policy=_policy(health_timeout_s=0.4))
    orig = fc.factory

    def factory(version):
        eng = orig(version)
        if version == v2:
            builds["n"] += 1
            if builds["n"] >= 2:      # canary passes; replica #2 is sick
                eng._breaker.trip()   # persistently not-ok (start() does
                #   not clear a tripped breaker)
        return eng

    fc.factory = factory
    try:
        res = fc.deploy(v2, canary_requests=2, canary_new_tokens=2)
        assert not res["ok"] and res["stage"] == "rollout"
        assert "failed its health gate" in res["reasons"][0]
        assert res["version"] is None        # still the previous version
        # EVERY replica — canary included — ends on the previous version
        assert set(fc._versions.values()) == {None}
        assert fc.rollout["state"] == "rolled_back"
        assert fc.stats["rollbacks"] == 1
        fc.router._probe_once()
        h = fc.health()
        assert h["ok"] and h["router"]["healthy"] == 3
        oks, errs = _resolve_all(
            [fc.submit(_prompt(), max_new_tokens=2) for _ in range(6)])
        assert len(oks) == 6 and not errs
        # the fleet can still promote a GOOD candidate afterwards
        v3 = _mk_bundle(tmp_path, "v3")
        res = fc.deploy(v3, canary_requests=2, canary_new_tokens=2)
        assert res["ok"] and set(fc._versions.values()) == {v3}
    finally:
        fc.stop()


def test_deploy_burn_bar_inherits_preexisting_burn(tmp_path):
    """Burn already in the sliding window at deploy start (a pre-deploy
    traffic spike) is NOT attributed to the candidate: the rollback bar
    inherits it, and only burn pushed PAST it triggers rollback (found
    by an e2e drive where a good candidate was rolled back for a burst
    that preceded the deploy)."""
    v2 = _mk_bundle(tmp_path, "v2")
    fc = _fleet(2)
    orig = fc.read_signal
    try:
        # the window reports burn 50 throughout — stale spike, flat
        fc.read_signal = lambda: dict(orig(), burn=50.0)
        res = fc.deploy(v2, canary_requests=2, canary_new_tokens=2)
        assert res["ok"], res["reasons"]
        assert set(fc._versions.values()) == {v2}
        # ...but burn GROWING past the inherited bar still rolls back
        v3 = _mk_bundle(tmp_path, "v3")
        burns = iter([50.0] + [80.0] * 10)   # first read = deploy start
        fc.read_signal = lambda: dict(orig(), burn=next(burns))
        res = fc.deploy(v3, canary_requests=2, canary_new_tokens=2)
        assert not res["ok"] and res["stage"] == "rollout"
        assert "rollback bar 50" in res["reasons"][0]
        assert set(fc._versions.values()) == {v2}
    finally:
        fc.read_signal = orig
        fc.stop()


# -- observability + renderers -----------------------------------------------

def test_fleet_metrics_flight_events_and_journey_spans(tmp_path):
    import paddlepaddle_tpu.observability as obs
    from paddlepaddle_tpu.observability import flight, reqtrace

    obs.reset()
    obs.enable(trace=False, metrics=True, watchdog_=False)
    flight.enable(capacity=256)
    reqtrace.enable()
    fc = None
    try:
        fc = _fleet(1, policy=_policy(max_replicas=2))
        _force_signal(fc, est_wait=5.0)
        for _ in range(2):
            fc._tick()
        _force_signal(fc, est_wait=0.0)
        for _ in range(3):
            fc._tick()
        v2 = _mk_bundle(tmp_path, "v2")
        res = fc.deploy(v2, canary_requests=1, canary_new_tokens=2)
        assert res["ok"]
        snap = obs.snapshot()
        assert sum(snap.get("paddle_fleet_scale_ups_total", {})
                   .values()) == 1
        assert sum(snap.get("paddle_fleet_scale_downs_total", {})
                   .values()) == 1
        assert sum(snap.get("paddle_fleet_rollouts_total", {})
                   .values()) == 1
        assert snap["paddle_fleet_replicas"][()] == 1
        assert snap["paddle_fleet_replicas_target"][()] == 1
        assert snap["paddle_fleet_scaleup_to_healthy_seconds"][()] >= 0
        text = obs.to_prometheus_text()
        assert "paddle_fleet_replicas" in text
        assert "paddle_fleet_scale_ups_total" in text
        events = [e for e in flight.get().events()
                  if e.get("kind") == "fleet"]
        kinds = {(e.get("data") or {}).get("event") for e in events}
        assert {"scale_up", "scale_down", "begin", "done"} <= kinds
        # fleet.scale / fleet.rollout spans land in the journey ring
        spans = [sp.get("name") for j in reqtrace.journeys()
                 for sp in j.spans]
        assert "fleet.scale" in spans and "fleet.rollout" in spans
    finally:
        if fc is not None:
            fc.stop()
        reqtrace.disable()
        flight.disable()
        obs.disable()
        obs.reset()


def test_obsctl_fleet_renders_the_block(capsys):
    from paddlepaddle_tpu.observability import exporter as _exporter

    spec = importlib.util.spec_from_file_location(
        "obsctl", os.path.join(_REPO, "tools", "obsctl.py"))
    obsctl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obsctl)
    e = _exporter.start(port=0)
    fc = None
    try:
        fc = _fleet(2)
        fc._tick()
        target = f"127.0.0.1:{e.port}"
        assert obsctl.main(["fleet", target]) == 0
        out = capsys.readouterr().out
        assert "replicas=2/target 2" in out
        assert "autoscaler: stopped" in out
        assert "rollout: idle" in out
        assert "last decision:" in out
        assert "r0" in out and "r1" in out
        assert obsctl.main(["fleet", target, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["fleet"]["replicas"] == 2
        # no fleet provider -> one stderr line, rc 1
        fc.stop()
        fc = None
        assert obsctl.main(["fleet", target]) == 1
        assert "no fleet provider" in capsys.readouterr().err
    finally:
        if fc is not None:
            fc.stop()
        _exporter.stop()


def test_drain_reason_labels_deliberate_scale_down():
    import paddlepaddle_tpu.observability as obs

    obs.reset()
    obs.enable(trace=False, metrics=True, watchdog_=False)
    eng = ServingEngine(FakeModel(delay_s=0.05), mode="static",
                        max_batch_size=1, max_len=64)
    try:
        futs = [eng.submit(_prompt(), max_new_tokens=2) for _ in range(4)]
        eng.drain(0.01, reason="scale_down")
        _resolve_all(futs, timeout=10)
        snap = obs.snapshot()
        shed = snap.get("paddle_serving_shed_total", {})
        assert sum(v for k, v in shed.items()
                   if dict(k).get("reason") == "scale_down") > 0
        drains = snap.get("paddle_serving_drains_total", {})
        assert any(dict(k).get("reason") == "scale_down"
                   for k in drains)
    finally:
        obs.disable()
        obs.reset()
        eng.stop()


# -- open-loop traffic + perf gate -------------------------------------------

def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_open_loop_traffic_helpers():
    sb = _load_tool("serving_bench")
    tr = sb.parse_traffic("step:4@5")
    assert tr == {"kind": "step", "mult": 4.0, "at_s": 5.0}
    rng = np.random.default_rng(0)
    offs = sb.arrival_offsets(tr, 2.0, 40, rng)
    assert offs == sorted(offs)
    pre = [b - a for a, b in zip(offs, offs[1:]) if b < 5.0]
    post = [b - a for a, b in zip(offs, offs[1:]) if a >= 5.0]
    assert all(abs(g - 0.5) < 1e-9 for g in pre)      # base rate 2/s
    assert all(abs(g - 0.125) < 1e-9 for g in post)   # 4x after the step
    po = sb.parse_traffic("poisson:8")
    offs = sb.arrival_offsets(po, 2.0, 4000, rng)
    assert abs(offs[-1] / 4000 - 0.125) < 0.02        # mean gap 1/rate
    for bad in ("step:4", "burst:2@1", "step:x@1", "poisson:zz"):
        with pytest.raises(ValueError):
            sb.parse_traffic(bad)
    # summary: drops counted, post-step p99 isolates the step window
    recs = [
        {"t_submit": 0.5, "outcome": "ok", "ttft_s": 0.05, "tokens": 8,
         "t_done": 0.9},
        {"t_submit": 5.5, "outcome": "ok", "ttft_s": 0.30, "tokens": 8,
         "t_done": 6.2},
        {"t_submit": 5.8, "outcome": "refused", "error": "X"},
        {"t_submit": 6.1, "outcome": "failed", "error": "Y"},
    ]
    s = sb.traffic_summary(recs, tr)
    assert s["dropped_requests"] == 2
    assert s["submitted"] == 4 and s["completed"] == 2
    assert s["step_ttft_p99_ms"] == 300.0     # only the post-step request
    assert s["ttft_p99_ms"] == 300.0
    w0 = next(w for w in s["windows"] if w["t_s"] == 0.0)
    assert w0["submitted"] == 1 and w0["completed"] == 1
    assert w0["tok_s"] == 8.0
    w5 = next(w for w in s["windows"] if w["t_s"] == 5.0)
    assert w5["submitted"] == 2 and w5["dropped"] == 1
    w6 = next(w for w in s["windows"] if w["t_s"] == 6.0)
    assert w6["dropped"] == 1 and w6["completed"] == 1


def test_perf_gate_fleet_fields(tmp_path):
    pg = _load_tool("perf_gate")
    base = {"serving_bench": {"traffic": {
        "step_ttft_p99_ms": 100.0, "dropped_requests": 0,
        "scaleup_to_healthy_s": 2.0}}}

    def rec(path, doc):
        p = os.path.join(str(tmp_path), path)
        with open(p, "w") as f:
            json.dump(doc, f)
        return p

    b = rec("base.json", base)
    bench = os.path.join(_REPO, "BENCH_r05.json")
    assert pg.main(["--baseline", bench, "--serving", b, b]) == 0
    # post-step TTFT regression past the latency budget fails
    worse = rec("ttft.json", {"serving_bench": {"traffic": {
        "step_ttft_p99_ms": 400.0, "dropped_requests": 0,
        "scaleup_to_healthy_s": 2.0}}})
    assert pg.main(["--baseline", bench, "--serving", worse, b]) == 1
    # dropped_requests is a HARD zero floor: 0 -> 1 fails regardless of
    # any relative budget
    dropped = rec("drop.json", {"serving_bench": {"traffic": {
        "step_ttft_p99_ms": 100.0, "dropped_requests": 1,
        "scaleup_to_healthy_s": 2.0}}})
    assert pg.main(["--baseline", bench, "--serving", dropped, b]) == 1
    # a slower scale-up (bundle arming broken) fails
    slow = rec("slow.json", {"serving_bench": {"traffic": {
        "step_ttft_p99_ms": 100.0, "dropped_requests": 0,
        "scaleup_to_healthy_s": 20.0}}})
    assert pg.main(["--baseline", bench, "--serving", slow, b]) == 1


def test_bundle_version_identity(tmp_path):
    from paddlepaddle_tpu.inference import compile_plan as cp

    bp = _mk_bundle(tmp_path, "v9")
    m = cp.read_manifest(bp)
    assert m["version"] == "v9-vid"
    assert cp.validate_bundle(bp)["version"] == "v9-vid"
    # a pre-version manifest gets a derived identity
    old = os.path.join(str(tmp_path), "old")
    os.makedirs(old)
    with open(os.path.join(old, "manifest.json"), "w") as f:
        json.dump({"format_version": 1, "created_unix": 1234.0,
                   "fingerprint": "a" * 64, "entries": []}, f)
    m = cp.read_manifest(old)
    assert m["version"] == f"{'a' * 12}@1234"
    assert cp.bundle_version_id("b" * 64, 7.9) == f"{'b' * 12}@7"
    # corruption is caught by validate (not by read)
    corrupt = _mk_bundle(tmp_path, "c", corrupt=True)
    with pytest.raises(cp.BundleMismatchError):
        cp.validate_bundle(corrupt)


# -- chaos drill -------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_4x_step_during_rollout_with_preemption(tmp_path):
    """Acceptance drill (real engines): a 4x open-loop traffic step lands
    WHILE a deploy rollout is walking the fleet, and one replica is
    preempted (killed abruptly) mid-rollout. Invariants: every submitted
    future resolves completed-or-typed (zero silently lost), the
    autoscaler reaches its target count, the rollout completes or rolls
    back cleanly (never a mixed-version fleet), and the fleet serves
    afterwards."""
    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddlepaddle_tpu.resilience.retry import RetryPolicy

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(
        vocab_size=64, hidden_size=32, layers=2, heads=4, kv_heads=2,
        max_len=128))

    def factory(version):
        return ServingEngine(model, max_batch_size=2, decode_chunk=4,
                             kv_page_size=16)

    pol = FleetPolicy(min_replicas=2, max_replicas=4,
                      scale_up_est_wait_s=0.15, up_streak=2,
                      down_streak=1000, cooldown_up_s=0.3,
                      cooldown_down_s=600.0, interval_s=0.1,
                      health_timeout_s=60.0, drain_timeout_s=15.0)
    fc = FleetController(factory, initial_replicas=2, policy=pol,
                         probe_interval_s=0.1,
                         retry_policy=RetryPolicy(max_attempts=8,
                                                  base_delay=0.02,
                                                  max_delay=0.2))
    fc.start(autoscaler=False)
    rng = np.random.default_rng(3)
    # warm every replica out-of-band so the drill measures scheduling,
    # not first compiles
    for rep in fc.router._replicas:
        rep.client.engine.generate(
            rng.integers(0, 64, (8,)).astype(np.int32), max_new_tokens=2)
    fc.router._probe_once()
    fc.start()                            # autoscaler loop on
    v2 = _mk_bundle(tmp_path, "v2")
    futs, lock, stop = [], threading.Lock(), threading.Event()
    deploy_result = {}

    def traffic():
        t0 = time.monotonic()
        while not stop.is_set():
            gap = 0.20 if time.monotonic() - t0 < 2.0 else 0.05   # 4x step
            p = rng.integers(0, 64, (int(rng.integers(4, 12)),)) \
                .astype(np.int32)
            try:
                f = fc.submit(p, max_new_tokens=3)
            except ServingError:
                time.sleep(gap)
                continue        # typed refusal: visible, not lost
            with lock:
                futs.append(f)
            time.sleep(gap)

    def deployer():
        deploy_result["res"] = fc.deploy(
            v2, canary_requests=2,
            canary_prompt=rng.integers(0, 64, (6,)).astype(np.int32),
            canary_new_tokens=2, canary_timeout=120)

    tthreads = [threading.Thread(target=traffic) for _ in range(2)]
    for t in tthreads:
        t.start()
    time.sleep(1.0)
    dthread = threading.Thread(target=deployer)
    dthread.start()
    time.sleep(1.5)
    # the preemption: one in-rotation replica dies abruptly mid-rollout
    victims = [r for r in fc.router._replicas if r.in_rotation]
    if victims:
        victims[0].client.kill()
    dthread.join(300)
    time.sleep(2.0)                       # let the step pressure register
    stop.set()
    for t in tthreads:
        t.join(30)
    try:
        res = deploy_result.get("res")
        assert res is not None, "deploy never finished"
        with lock:
            taken = list(futs)
        assert len(taken) > 20, "the drill must run under real traffic"
        oks, errs = _resolve_all(taken, timeout=120)
        # zero lost futures: everything resolved, failures are typed/known
        assert len(oks) + len(errs) == len(taken)
        for e in errs:
            assert isinstance(e, (ServingError, RuntimeError,
                                  ConnectionError)), e
        # the fleet absorbed the step: the overwhelming majority completed
        assert len(oks) >= len(taken) * 0.8, \
            f"only {len(oks)}/{len(taken)} completed"
        # the autoscaler reached its target under the step
        assert len(fc.router._replicas) >= 2
        assert fc.target == len(fc.router._replicas)
        # rollout completed or rolled back CLEANLY: never a mixed fleet
        assert res["stage"] in ("done", "canary", "rollout"), res
        live_versions = {fc._versions[r.name]
                         for r in fc.router._replicas}
        if res["ok"]:
            assert fc.rollout["state"] == "done"
            assert live_versions == {v2}
        else:
            assert fc.rollout["state"] == "rolled_back"
            assert live_versions == {None}
        # and the fleet still serves
        fc.router._probe_once()
        out = fc.generate(rng.integers(0, 64, (8,)).astype(np.int32),
                          max_new_tokens=3, timeout=300)
        assert out.shape == (11,)
    finally:
        fc.stop()
