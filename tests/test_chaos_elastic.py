"""End-to-end chaos drill: the elastic loop survives an injected worker
kill (launch --max_restarts + CheckpointManager resume) and a corrupted
checkpoint shard (newest-valid fallback).

The worker kill is a chaos-engine injection (``preempt:kill:@1``) armed only
in rank 1's first incarnation; the restarted incarnation sees
``PADDLE_RESTART_NUM=1`` and resumes from the newest valid checkpoint. The
final loss must equal an uninterrupted single-worker run of the same
schedule (fixed full batch → allreduce-mean trajectory is world-size
independent).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

_WORKER = r"""
import os, sys, time
sys.path.insert(0, os.environ["REPO_DIR"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.distributed.host_collectives import get_host_group
from paddlepaddle_tpu.resilience import CheckpointManager, chaos
from paddlepaddle_tpu.resilience.chaos import chaos_point
from paddlepaddle_tpu.resilience.integrity import find_latest_valid_checkpoint

rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
incarnation = int(os.environ.get("PADDLE_RESTART_NUM", "0"))
root = os.environ["DRILL_ROOT"]
out_path = os.environ["DRILL_OUT"]
kill_step = int(os.environ.get("DRILL_KILL_STEP", "-1"))
TOTAL = 10

# chaos armed ONLY for rank 1's first incarnation: one deterministic kill
if rank == 1 and incarnation == 0 and kill_step >= 0:
    chaos.configure("preempt:kill:@1:77",
                    seed=int(os.environ.get("PADDLE_CHAOS_SEED", "0")))

g = get_host_group() if world > 1 else None
mgr = CheckpointManager(root, keep_last_k=3)

lin = paddle.nn.Linear(4, 1)
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
start = mgr.restore(lin.state_dict()) or 0
if g is not None and start:
    # rejoin the collective stream at the exact op index derivable from the
    # checkpoint: one all_reduce per parameter per finished step
    g.rejoin(start * len(lin.parameters()))

rng = np.random.default_rng(0)
xb = rng.standard_normal((16, 4)).astype(np.float32)
w_true = np.asarray([[1.0], [2.0], [-1.0], [0.5]], np.float32)
yb = xb @ w_true

loss_val = None
for step in range(start, TOTAL):
    if rank == 1 and incarnation == 0 and step == kill_step:
        # cross the kill seam only once the checkpoint for THIS step is
        # committed, so the restarted incarnation resumes exactly here
        deadline = time.time() + 60
        while time.time() < deadline:
            latest = find_latest_valid_checkpoint(root)
            if latest is not None and latest[0] >= step:
                break
            time.sleep(0.05)
        chaos_point("preempt")  # armed above: os._exit(77)
    loss = ((lin(paddle.to_tensor(xb)) - paddle.to_tensor(yb)) ** 2).mean()
    loss.backward()
    if g is not None:
        for p in lin.parameters():
            p.grad = paddle.to_tensor(
                g.all_reduce(np.asarray(p.grad.numpy()), op="sum") / world)
    opt.step()
    opt.clear_grad()
    loss_val = float(loss.numpy())
    if rank == 0:
        # every rank holds the full replicated state (allreduced grads):
        # rank 0 alone commits it through the atomic single-host path
        mgr.save(lin.state_dict(), step + 1,
                 process_index=0, process_count=1)

if rank == 0:
    with open(out_path, "w") as f:
        f.write(repr(loss_val))
print(f"CHAOS_RANK{rank}_DONE loss={loss_val} incarnation={incarnation}")
"""


def _run(tmp_path, tag, world, kill_step):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    d = tmp_path / tag
    d.mkdir()
    script = d / "train.py"
    script.write_text(_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               REPO_DIR=repo, PADDLE_CHAOS_SEED="1234",
               DRILL_ROOT=str(d / "ckpts"),
               DRILL_OUT=str(d / "final_loss.txt"),
               DRILL_KILL_STEP=str(kill_step))
    out = subprocess.run(
        [sys.executable, "-m", "paddlepaddle_tpu.distributed.launch",
         "--nproc_per_node", str(world), "--max_restarts", "2", str(script)],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    return out, d, float((d / "final_loss.txt").read_text())


@pytest.mark.slow
def test_injected_kill_resumes_from_checkpoint_matching_loss(tmp_path):
    out, d, interrupted = _run(tmp_path, "duo_kill", world=2, kill_step=6)
    assert "worker 1 exited 77" in out.stderr  # the chaos kill fired
    assert "restart 1/2" in out.stderr          # the launcher respawned it
    _out2, _d2, baseline = _run(tmp_path, "solo", world=1, kill_step=-1)
    np.testing.assert_allclose(interrupted, baseline, rtol=1e-6)

    # second half of the acceptance drill: corrupt the newest surviving
    # checkpoint shard; restore must fall back to the last VALID one
    from paddlepaddle_tpu.distributed import checkpoint as dist_ckpt
    from paddlepaddle_tpu.resilience import CheckpointManager
    from paddlepaddle_tpu.resilience.integrity import list_checkpoints

    import paddlepaddle_tpu as paddle

    root = str(d / "ckpts")
    steps = [s for s, _ in list_checkpoints(root)]
    assert steps == [10, 9, 8]  # keep_last_k=3 GC ran under the launcher
    mgr = CheckpointManager(root, keep_last_k=3)
    newest = mgr.step_path(10)
    meta = dist_ckpt.get_checkpoint_metadata(newest)
    victim = os.path.join(
        newest, meta["tensors"]["weight"]["shards"][0]["file"])
    with open(victim, "r+b") as f:
        f.seek(-3, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    lin = paddle.nn.Linear(4, 1)
    assert mgr.restore(lin.state_dict()) == 9  # skipped the corrupt newest


@pytest.mark.slow
def test_launcher_sigterm_drains_without_respawn(tmp_path):
    """A SIGTERMed launcher (preempted job) forwards the TERM, drains the
    workers, and exits 143 WITHOUT burning restarts respawning them."""
    import signal
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "sleeper.py"
    script.write_text(
        "import sys, time\n"
        "sys.stdout.write('WORKER_UP\\n'); sys.stdout.flush()\n"
        "time.sleep(120)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddlepaddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", "3", str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=repo)
    try:
        assert proc.stdout.readline().strip() == "WORKER_UP"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    err = proc.stderr.read()
    assert rc == 143, (rc, err[-2000:])
    assert "no restarts" in err
    assert "restart 1/3" not in err  # the old handler respawned here
