"""Sharding-efficiency regression tests: assertions on compiled HLO.

The reference hand-places its collectives (mp_layers.py masks+allreduces the
vocab-sharded embedding; sharding stages reduce-scatter gradients); here XLA
places them from shardings, so these tests pin the *compiled artifact*:

* the GSPMD train step compiles without XLA's "Involuntary full
  rematerialization" fallback (a replicate-then-repartition reshard);
* the vocab-sharded embedding lookup never all-gathers the full-vocab table;
* fsdp gradient reduction uses reduce-scatter, not replicated all-reduce;
* the pipeline's scan body carries exactly its two ring collective-permutes.
"""

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; fast tier covers this module via test_fast_smokes.py

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def _llama_step(data_axes=("dp", "fsdp")):
    from paddlepaddle_tpu.distributed.mesh import ProcessMesh
    from paddlepaddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                         llama_sharding_rules)
    from paddlepaddle_tpu.optimizer import AdamW
    from paddlepaddle_tpu.parallel import ShardedTrainStep

    cfg = LlamaConfig.tiny(vocab_size=256, hidden_size=64, layers=2, heads=4,
                           kv_heads=2, max_len=128)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    mesh = ProcessMesh(shape=[2, 2, 2], dim_names=["dp", "fsdp", "tp"])
    return ShardedTrainStep(model, opt,
                            loss_fn=lambda m, i, l: m(i, labels=l),
                            mesh=mesh, rules=llama_sharding_rules(),
                            data_axes=data_axes)


def _compiled_text(step, batch=8, seq=64):
    import jax.numpy as jnp

    import paddlepaddle_tpu.core.random as prandom

    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (batch, seq)),
                      jnp.int32)
    low = step._step.lower(step.params, step.buffers, step.opt_state,
                           (ids, ids), prandom.next_key(),
                           jnp.asarray(1e-3, jnp.float32))
    return low.compile().as_text()


def test_train_step_compiles_without_forced_remat(capfd):
    """The dp x fsdp x tp step must not hit XLA's replicate-and-repartition
    fallback (round-1 dryrun warning; fixed by the embed (fsdp, tp) rule)."""
    step = _llama_step()
    _compiled_text(step)
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err


def test_embedding_never_allgathers_full_vocab():
    """mp_layers.py:49 masks+allreduces instead of gathering the [V, h] table;
    XLA must likewise never materialize the full vocab dim of the embedding
    (or lm_head) on one device."""
    step = _llama_step()
    txt = _compiled_text(step)
    for line in txt.splitlines():
        # an all-gather whose RESULT carries the full 256-vocab dim
        if "all-gather(" in line and "= f32[256," in line:
            pytest.fail(f"full-vocab all-gather in compiled HLO: {line.strip()[:160]}")


def test_fsdp_grad_reduction_stays_sharded():
    """ZeRO semantics (group_sharded_stage2/3): gradient reduction must keep
    each device holding only its gradient shard — no all-reduce may produce a
    FULL (global-shaped) weight gradient. (XLA:CPU decomposes reduce-scatter,
    so we pin the invariant, not the instruction name: on TPU the same
    shardings lower to reduce-scatter over ICI.)"""
    step = _llama_step()
    global_shapes = {tuple(p.shape) for p in step.params.values()
                     if len(p.shape) == 2}  # the fsdp/tp-sharded matmul weights
    txt = _compiled_text(step)
    for line in txt.splitlines():
        if "all-reduce(" not in line:
            continue
        head = line.split("all-reduce(")[0]
        import re

        m = re.search(r"f32\[([0-9,]+)\]", head)
        if not m:
            continue
        shape = tuple(int(x) for x in m.group(1).split(","))
        assert shape not in global_shapes, (
            f"all-reduce materializes a FULL weight gradient {shape}: "
            f"{line.strip()[:140]}")


def test_vocab_parallel_embedding_no_table_allgather():
    """mpu.VocabParallelEmbedding trusts XLA's partitioned gather; pin that
    the lowering never all-gathers the [V, h] vocab-sharded table (the
    reference instead masks + allreduces, mp_layers.py:49)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    V, H = 512, 64
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))
    table = jax.device_put(
        jnp.asarray(np.random.default_rng(0).standard_normal((V, H)),
                    jnp.float32), NamedSharding(mesh, P("mp", None)))
    ids = jax.device_put(
        jnp.asarray(np.random.default_rng(1).integers(0, V, (8, 16)), jnp.int32),
        NamedSharding(mesh, P("dp", None)))

    def lookup_loss(w, i):
        return jnp.sum(jnp.take(w, i, axis=0) ** 2)

    txt = jax.jit(jax.value_and_grad(lookup_loss)).lower(table, ids
                                                         ).compile().as_text()
    for line in txt.splitlines():
        if "all-gather(" in line and f"= f32[{V}," in line:
            pytest.fail(f"vocab table all-gathered: {line.strip()[:140]}")


def test_pipeline_scan_has_two_ring_permutes():
    """spmd_pipeline_train: one up-ring and one down-ring collective-permute
    per slot, carried inside the scan while-body — not unrolled per slot."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddlepaddle_tpu.parallel.pipeline_spmd import (
        spmd_pipeline_train, stack_stage_params)

    S, M, B, h = 4, 8, 16, 8
    stages = [{"w": jnp.eye(h, dtype=jnp.float32)} for _ in range(S)]
    head = {"wo": jnp.eye(h, dtype=jnp.float32)}
    x = jnp.ones((B, h), jnp.float32)
    y = jnp.ones((B, h), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))

    def block(p, a):
        return jnp.tanh(a @ p["w"])

    def head_loss(hp, a, t):
        return jnp.mean((a @ hp["wo"] - t) ** 2)

    def run(sp, hp, x_, y_):
        return spmd_pipeline_train(sp, hp, x_, y_, block, head_loss, mesh,
                                   schedule="1f1b", n_microbatches=M,
                                   pp_axis="pp")

    txt = jax.jit(run).lower(stack_stage_params(stages), head, x, y
                             ).compile().as_text()
    n_permute = txt.count("collective-permute(")
    assert n_permute == 2, f"expected 2 ring permutes in scan body, got {n_permute}"
