"""paddle.onnx.export round-trip: export real layers, decode the emitted
protobuf with an independent generic wire-format parser, execute the graph
with a numpy ONNX interpreter, and compare against the framework forward.

This validates both the hand-rolled serialization (structure decodes
cleanly, tensors round-trip) and the jaxpr->ONNX conversion semantics
(numerics match). Field-number constants mirror the public onnx.proto."""

import struct

import numpy as np
import pytest
import scipy.special

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.nn as nn

# ------------------------------------------------------- protobuf decoding


def _parse(buf):
    """Generic wire parse: {field: [(wire_type, value), ...]} in order."""
    out = {}
    i = 0
    while i < len(buf):
        key = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            key |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field, wt = key >> 3, key & 7
        if wt == 0:
            v = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            v = buf[i:i + ln]
            assert len(v) == ln, "truncated length-delimited field"
            i += ln
        elif wt == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wt == 1:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise AssertionError(f"unexpected wire type {wt}")
        out.setdefault(field, []).append((wt, v))
    return out


def _signed(v):
    return v - (1 << 64) if v >= 1 << 63 else v


def _packed_varints(b):
    vals = []
    i = 0
    while i < len(b):
        v = 0
        shift = 0
        while True:
            x = b[i]
            i += 1
            v |= (x & 0x7F) << shift
            shift += 7
            if not x & 0x80:
                break
        vals.append(_signed(v))
    return vals


_ONNX_NP = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
            10: np.float16, 11: np.float64, 2: np.uint8, 3: np.int8}


def _tensor(b):
    f = _parse(b)
    dims = _packed_varints(f[1][0][1]) if 1 in f else []
    dt = f[2][0][1]
    name = f.get(8, [(2, b"")])[0][1].decode()
    raw = f.get(9, [(2, b"")])[0][1]
    if dt == 16:  # bfloat16
        import ml_dtypes
        arr = np.frombuffer(raw, np.uint16).view(ml_dtypes.bfloat16)
    else:
        arr = np.frombuffer(raw, _ONNX_NP[dt])
    return name, arr.reshape(dims).copy()


def _attr(b):
    f = _parse(b)
    name = f[1][0][1].decode()
    at = f[20][0][1]
    if at == 2:
        return name, _signed(f[3][0][1])
    if at == 1:
        return name, f[2][0][1]
    if at == 3:
        return name, f[4][0][1].decode()
    if at == 7:
        return name, [_signed(v) for _, v in f.get(8, [])]
    if at == 6:
        return name, [v for _, v in f.get(7, [])]
    if at == 4:
        return name, _tensor(f[5][0][1])
    raise AssertionError(f"attr type {at}")


def _node(b):
    f = _parse(b)
    return {
        "inputs": [v.decode() for _, v in f.get(1, [])],
        "outputs": [v.decode() for _, v in f.get(2, [])],
        "op": f[4][0][1].decode(),
        "attrs": dict(_attr(a) for _, a in f.get(5, [])),
    }


def _value_info(b):
    f = _parse(b)
    name = f[1][0][1].decode()
    tt = _parse(_parse(f[2][0][1])[1][0][1])
    elem = tt[1][0][1]
    dims = []
    for _, d in _parse(tt[2][0][1]).get(1, []):
        df = _parse(d)
        dims.append(df[1][0][1] if 1 in df else df[2][0][1].decode())
    return name, elem, dims


def load_model(path):
    f = _parse(open(path, "rb").read())
    assert 1 in f and 7 in f, "missing ir_version/graph"
    opset = _parse(f[8][0][1])
    assert _signed(opset[2][0][1]) >= 13
    g = _parse(f[7][0][1])
    return {
        "nodes": [_node(n) for _, n in g.get(1, [])],
        "inits": dict(_tensor(t) for _, t in g.get(5, [])),
        "inputs": [_value_info(v) for _, v in g.get(11, [])],
        "outputs": [_value_info(v) for _, v in g.get(12, [])],
    }


# ------------------------------------------------------ numpy interpreter


def _np_slice(x, starts, ends, axes, steps):
    sl = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        dim = x.shape[a]
        if st < 0 and e < -dim:
            e = None
        sl[a] = slice(s, e, st)
    return x[tuple(sl)]


def _pool(x, kernel, strides, pads, mode, dilations=None, include_pad=False):
    n, c, H, W = x.shape
    kh, kw = kernel
    dh, dw = dilations or (1, 1)
    fill = -np.inf if mode == "max" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])),
                constant_values=fill)
    Ho = (xp.shape[2] - (dh * (kh - 1) + 1)) // strides[0] + 1
    Wo = (xp.shape[3] - (dw * (kw - 1) + 1)) // strides[1] + 1
    out = np.empty((n, c, Ho, Wo), x.dtype)
    for i in range(Ho):
        for j in range(Wo):
            win = xp[:, :, i * strides[0]:i * strides[0] + dh * (kh - 1) + 1:dh,
                     j * strides[1]:j * strides[1] + dw * (kw - 1) + 1:dw]
            out[:, :, i, j] = win.max((2, 3)) if mode == "max" \
                else win.mean((2, 3))
    return out


def _conv(x, w, b, strides, pads, dilations, group):
    n, cin, H, W = x.shape
    cout, cpg, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    Ho = (xp.shape[2] - (dilations[0] * (kh - 1) + 1)) // strides[0] + 1
    Wo = (xp.shape[3] - (dilations[1] * (kw - 1) + 1)) // strides[1] + 1
    out = np.zeros((n, cout, Ho, Wo), np.float64)
    cog = cout // group
    for g in range(group):
        xg = xp[:, g * cpg:(g + 1) * cpg]
        wg = w[g * cog:(g + 1) * cog]
        for i in range(kh):
            for j in range(kw):
                xs = xg[:, :, i * dilations[0]:i * dilations[0]
                        + Ho * strides[0]:strides[0],
                        j * dilations[1]:j * dilations[1]
                        + Wo * strides[1]:strides[1]]
                out[:, g * cog:(g + 1) * cog] += np.einsum(
                    "nchw,oc->nohw", xs, wg[:, :, i, j])
    if b is not None:
        out += b[None, :, None, None]
    return out.astype(x.dtype)


def run_model(m, feeds):
    env = dict(m["inits"])
    env.update(feeds)
    for nd in m["nodes"]:
        i = [env[k] for k in nd["inputs"]]
        a = nd["attrs"]
        op = nd["op"]
        if op == "Identity":
            r = i[0]
        elif op in ("Add", "Sub", "Mul", "Div", "Pow"):
            r = {"Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
                 "Div": np.divide, "Pow": np.power}[op](i[0], i[1])
        elif op == "Max":
            r = np.maximum(i[0], i[1])
        elif op == "Min":
            r = np.minimum(i[0], i[1])
        elif op == "Mod":
            r = np.fmod(i[0], i[1]) if a.get("fmod") else np.mod(i[0], i[1])
        elif op == "Neg":
            r = -i[0]
        elif op == "Exp":
            r = np.exp(i[0])
        elif op == "Log":
            r = np.log(i[0])
        elif op == "Sqrt":
            r = np.sqrt(i[0])
        elif op == "Reciprocal":
            r = 1.0 / i[0]
        elif op == "Abs":
            r = np.abs(i[0])
        elif op == "Sigmoid":
            r = 1.0 / (1.0 + np.exp(-i[0]))
        elif op == "Tanh":
            r = np.tanh(i[0])
        elif op == "Erf":
            r = scipy.special.erf(i[0])
        elif op == "Sin":
            r = np.sin(i[0])
        elif op == "Cos":
            r = np.cos(i[0])
        elif op == "Not":
            r = ~i[0]
        elif op in ("Less", "Greater", "Equal", "LessOrEqual",
                    "GreaterOrEqual"):
            r = {"Less": np.less, "Greater": np.greater, "Equal": np.equal,
                 "LessOrEqual": np.less_equal,
                 "GreaterOrEqual": np.greater_equal}[op](i[0], i[1])
        elif op == "Where":
            r = np.where(i[0], i[1], i[2])
        elif op == "Clip":
            r = np.clip(i[0], i[1], i[2])
        elif op == "Cast":
            np_dt = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
                     10: np.float16, 11: np.float64}[a["to"]]
            r = i[0].astype(np_dt)
        elif op == "Transpose":
            r = np.transpose(i[0], a["perm"])
        elif op == "Reshape":
            r = i[0].reshape([int(d) for d in i[1]])
        elif op == "Expand":
            r = np.broadcast_to(
                i[0], np.broadcast_shapes(i[0].shape,
                                          tuple(int(d) for d in i[1])))
        elif op == "Concat":
            r = np.concatenate(i, axis=a["axis"])
        elif op == "Slice":
            r = _np_slice(i[0], *[list(map(int, v)) for v in i[1:]])
        elif op == "Pad":
            p = [int(v) for v in i[1]]
            nd_ = i[0].ndim
            r = np.pad(i[0], list(zip(p[:nd_], p[nd_:])),
                       constant_values=i[2] if len(i) > 2 else 0)
        elif op == "ReduceSum":
            r = i[0].sum(tuple(int(v) for v in i[1]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd"):
            fn_ = {"ReduceMax": np.max, "ReduceMin": np.min,
                   "ReduceProd": np.prod}[op]
            r = fn_(i[0], tuple(a["axes"]),
                    keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ArgMax", "ArgMin"):
            fn_ = np.argmax if op == "ArgMax" else np.argmin
            r = fn_(i[0], a["axis"]).astype(np.int64)
            if a.get("keepdims", 1):
                r = np.expand_dims(r, a["axis"])
        elif op == "CumSum":
            ax = int(i[1])
            r = np.flip(np.cumsum(np.flip(i[0], ax), ax), ax) \
                if a.get("reverse") else np.cumsum(i[0], ax)
        elif op == "Einsum":
            r = np.einsum(a["equation"], *i)
        elif op == "Gather":
            r = np.take(i[0], i[1].astype(np.int64), axis=a.get("axis", 0))
        elif op == "Conv":
            r = _conv(i[0], i[1], i[2] if len(i) > 2 else None,
                      a["strides"], a["pads"], a["dilations"], a["group"])
        elif op == "MaxPool":
            r = _pool(i[0], a["kernel_shape"], a["strides"], a["pads"],
                      "max", a.get("dilations"))
        elif op == "AveragePool":
            assert a.get("count_include_pad") == 1
            r = _pool(i[0], a["kernel_shape"], a["strides"], a["pads"],
                      "avg", include_pad=True)
        else:
            raise AssertionError(f"interpreter: unknown op {op}")
        env[nd["outputs"][0]] = np.asarray(r)
    return [env[name] for name, _, _ in m["outputs"]]


# ----------------------------------------------------------------- tests


def _roundtrip(layer, inputs, path, rtol=1e-4, atol=1e-5):
    paddle.onnx.export(layer, str(path),
                       input_spec=[paddle.to_tensor(x) for x in inputs])
    m = load_model(str(path) + ".onnx")
    got = run_model(m, {f"x{i}": x for i, x in enumerate(inputs)})
    layer.eval()
    want = layer(*[paddle.to_tensor(x) for x in inputs])
    want = want if isinstance(want, (list, tuple)) else [want]
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w.numpy(), rtol=rtol, atol=atol)
    return m


def test_export_mlp_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    mlp = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.LayerNorm(16),
                        nn.Linear(16, 4), nn.Softmax(-1))
    x = rng.standard_normal((3, 8)).astype(np.float32)
    m = _roundtrip(mlp, [x], tmp_path / "mlp")
    ops = {n["op"] for n in m["nodes"]}
    assert "Einsum" in ops and "Erf" in ops
    # params became initializers, graph input is only x0
    assert [v[0] for v in m["inputs"]] == ["x0"]
    assert any(k.startswith("p_") for k in m["inits"])


def test_export_convnet_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    net = nn.Sequential(
        nn.Conv2D(3, 8, 3, stride=2, padding=1),
        nn.BatchNorm2D(8), nn.ReLU(),
        nn.Conv2D(8, 8, 3, padding=1, groups=2),
        nn.MaxPool2D(2, 2),
        nn.AvgPool2D(2, 2),
        nn.Flatten(), nn.Linear(8 * 2 * 2, 5))
    net.eval()
    x = rng.standard_normal((2, 3, 16, 16)).astype(np.float32)
    m = _roundtrip(net, [x], tmp_path / "conv", rtol=1e-3, atol=1e-4)
    ops = [n["op"] for n in m["nodes"]]
    assert "Conv" in ops and "MaxPool" in ops and "AveragePool" in ops
    conv = next(n for n in m["nodes"] if n["op"] == "Conv"
                and n["attrs"]["group"] == 2)
    assert conv["attrs"]["pads"] == [1, 1, 1, 1]


def test_export_embedding_and_opset_upgrade(tmp_path):
    rng = np.random.default_rng(2)

    class Emb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(12, 6)
            self.fc = nn.Linear(6, 3)

        def forward(self, ids):
            return self.fc(self.emb(ids))

    ids = rng.integers(0, 12, (2, 5)).astype(np.int32)
    # the reference's default opset 9 upgrades silently to 13
    paddle.onnx.export(Emb(), str(tmp_path / "emb"),
                       input_spec=[paddle.to_tensor(ids)],
                       opset_version=9)
    m = load_model(str(tmp_path / "emb") + ".onnx")
    got = run_model(m, {"x0": ids})
    assert got[0].shape == (2, 5, 3)
    assert any(n["op"] == "Gather" for n in m["nodes"])


def test_export_unsupported_primitive_raises(tmp_path):
    class Sorter(nn.Layer):
        def forward(self, x):
            return paddle.sort(x, axis=-1)

    with pytest.raises(NotImplementedError, match="sort"):
        paddle.onnx.export(Sorter(), str(tmp_path / "s"),
                           input_spec=[paddle.to_tensor(
                               np.zeros((2, 3), np.float32))])


def test_export_path_validation(tmp_path):
    with pytest.raises(ValueError, match="file_prefix"):
        paddle.onnx.export(nn.Linear(2, 2), str(tmp_path) + "/")


def test_export_dynamic_batch_dim_param(tmp_path):
    """A None/-1 input-spec dim exports as a symbolic dim_param (not a
    fixed 1) and the pinning is warned about (r4 advisor finding)."""
    from paddlepaddle_tpu.static import InputSpec

    mlp = nn.Sequential(nn.Linear(8, 4), nn.ReLU())
    with pytest.warns(UserWarning, match="dim_param"):
        paddle.onnx.export(
            mlp, str(tmp_path / "dyn"),
            input_spec=[InputSpec([None, 8], "float32", "x")])
    m = load_model(str(tmp_path / "dyn") + ".onnx")
    (name, _elem, dims), = m["inputs"]
    assert name == "x0"
    assert isinstance(dims[0], str) and dims[1] == 8
    # outputs must agree on what is symbolic (consistent shape inference)
    (oname, _oelem, odims), = m["outputs"]
    assert isinstance(odims[0], str) and odims[1] == 4
    # the traced graph is batch-agnostic for an MLP: runs at batch 3
    x = np.random.default_rng(3).standard_normal((3, 8)).astype(np.float32)
    got = run_model(m, {"x0": x})
    assert got[0].shape == (3, 4)
