"""Profiling & waste-attribution plane (observability/profiler.py,
goodput.py, memledger.py + engine/serving accounting seams).

The acceptance surface: the sampling profiler classifies stacks by seam
and pins the decode seam as #1 under a decode-shaped load (rendered by
``obsctl profile`` against a live exporter); the goodput ledger
RECONCILES — through a chaos run with speculation, a mid-flight
hedge-loser cancel and a hard stop, useful + attributed waste equals the
engine's ``tokens_out`` EXACTLY and zero KV pages leak; the memory
ledger buckets live HBM and the default ruleset grows ``waste_burn`` +
``hbm_headroom``. The prof-on hot-path budgets live in
tools/check_obs_overhead.py (gate 7) and tools/check_serving_overhead.py
(prof-on leg), not here.
"""

import json
import os
import queue
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.observability as obs
from paddlepaddle_tpu.observability import (
    aggregate,
    exporter,
    flight,
    goodput,
    memledger,
    profiler,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obsctl():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obsctl", os.path.join(_REPO, "tools", "obsctl.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_planes():
    """Goodput/profiler/memledger singletons fully reset before AND
    after — the goodput ledger is global and accumulates across suites."""
    goodput.reset()
    profiler.reset()
    memledger.reset()
    flight.disable()
    exporter.stop()
    yield
    goodput.reset()
    profiler.reset()
    memledger.reset()
    flight.disable()
    exporter.stop()
    obs.disable()
    obs.reset()


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---------------------------------------------------------------------------
# goodput ledger units
# ---------------------------------------------------------------------------

def test_goodput_ledger_counts_and_window(clean_planes):
    led = goodput.GoodputLedger(window_s=60.0)
    led.account("useful", 90, now=1000.0)
    led.account("hedge_loser", 10, now=1001.0)
    snap_window = led.waste_pct(now=1002.0)
    assert snap_window == pytest.approx(10.0)
    # the old events age out of the window; cumulative counts do not
    led.account("useful", 5, now=2000.0)
    assert led.waste_pct(now=2000.0) == pytest.approx(0.0)
    snap = led.snapshot()
    assert snap["kinds"]["useful"] == 95
    assert snap["kinds"]["hedge_loser"] == 10
    assert snap["decoded_tokens"] == 105
    assert snap["waste_pct"] == pytest.approx(100.0 * 10 / 105, abs=0.01)
    with pytest.raises(ValueError):
        led.account("not_a_kind", 1)
    # the module-level seam never raises (it guards the engine hot path)
    goodput.account("not_a_kind", 1)
    led.reset()
    assert led.snapshot()["decoded_tokens"] == 0
    assert led.waste_pct(now=3000.0) is None


def test_goodput_spec_rejected_outside_decoded_identity(clean_planes):
    led = goodput.GoodputLedger()
    led.account("useful", 10)
    led.account("spec_rejected", 7)
    snap = led.snapshot()
    assert snap["decoded_tokens"] == 10      # drafts never hit tokens_out
    assert snap["wasted_tokens"] == 7        # ... but they are real waste
    assert set(goodput.DECODED_KINDS) == set(goodput.KINDS) - {
        "spec_rejected"}


# ---------------------------------------------------------------------------
# sampling profiler: classification + decode-seam pin
# ---------------------------------------------------------------------------

def test_classify_seams_and_idle_innermost_only():
    assert profiler.classify(
        [("decode_engine.py", "_decode_chunk")]) == "decode"
    assert profiler.classify([("serving.py", "_sweep_slots")]) == "admission"
    assert profiler.classify([("router.py", "_maybe_hedge")]) == "router"
    assert profiler.classify([("socket.py", "recv_into")]) == "wire"
    assert profiler.classify([("threading.py", "wait")]) == "idle"
    # idle matches the INNERMOST frame only: an engine frame above a
    # helper's wait() still reads as decode, not idle
    assert profiler.classify(
        [("speculative.py", "_spec_chunk"), ("threading.py", "wait")]
    ) == "decode"
    assert profiler.classify([("mymodule.py", "work")]) == "other"


def _busy_decode_thread():
    """A thread whose hot frame is literally named like the engine's
    decode seam — the synthetic load the decode-seam pin samples."""
    stop = threading.Event()

    def _decode_chunk():        # the name IS the classification input
        while not stop.is_set():
            sum(range(200))

    t = threading.Thread(target=_decode_chunk, daemon=True,
                         name="fake-decode")
    t.start()
    return stop, t


def test_profiler_pins_decode_seam_hot(clean_planes):
    stop, t = _busy_decode_thread()
    blocked = threading.Thread(target=queue.Queue().get, daemon=True,
                               name="parked")
    blocked.start()
    try:
        prof = profiler.SamplingProfiler(hz=50.0, window_s=60.0)
        for _ in range(40):
            prof.sample_once()
        rows = prof.hot_stacks(seconds=60.0, n=10)
        assert rows, "no stacks sampled"
        assert rows[0]["category"] == "decode"
        assert rows[0]["thread"] == "fake-decode"
        assert rows[0]["leaf"].endswith(":_decode_chunk")
        cats = prof.categories(60.0)
        assert cats.get("decode", 0) >= 40       # every tick saw it
        assert cats.get("idle", 0) >= 1          # the parked thread
        # flamegraph-ready collapsed: "folded;stack count" lines
        coll = prof.collapsed()
        line = next(ln for ln in coll.splitlines()
                    if ln.startswith("decode;fake-decode;"))
        assert int(line.rsplit(" ", 1)[1]) >= 40
        j = prof.jsonable(seconds=60.0, n=5)
        assert j["samples"] >= 40 and j["ticks"] == 40
        assert j["top"][0]["category"] == "decode"
    finally:
        stop.set()
        t.join(timeout=5)


def test_profiler_enable_disable_idempotent(clean_planes):
    p1 = profiler.enable(hz=200.0, start_thread=False)
    assert profiler.enable(start_thread=False) is p1
    assert profiler.get() is p1
    profiler.disable()
    assert profiler.get() is None


# ---------------------------------------------------------------------------
# /profile + /mem endpoints and obsctl rendering
# ---------------------------------------------------------------------------

def test_profile_endpoint_503_when_off_then_serves(clean_planes, tmp_path,
                                                   capsys):
    obsctl = _load_obsctl()
    stop, t = _busy_decode_thread()
    try:
        with exporter.TelemetryExporter(port=0) as e:
            status, body = _get(e.url("/profile"))
            assert status == 503
            assert json.loads(body)["enabled"] is False

            prof = profiler.enable(start_thread=False)
            for _ in range(25):
                prof.sample_once()

            status, body = _get(e.url("/profile?seconds=120&top=5"))
            assert status == 200
            doc = json.loads(body)
            assert doc["enabled"] is True
            assert doc["top"][0]["category"] == "decode"

            status, body = _get(
                e.url("/profile?seconds=120&format=collapsed"))
            assert status == 200
            assert b"decode;fake-decode;" in body

            status, _ = _get(e.url("/profile?seconds=bogus"))
            assert status == 400

            # obsctl profile: the operator's top-N hot-stack table
            target = f"127.0.0.1:{e.port}"
            out_file = str(tmp_path / "stacks.collapsed")
            assert obsctl.main(["profile", target, "-s", "120", "-n", "5",
                                "--collapsed", out_file]) == 0
            rendered = capsys.readouterr().out
            first_row = next(ln for ln in rendered.splitlines()
                             if ln.strip().startswith("1 "))
            assert "decode" in first_row and "_decode_chunk" in first_row
            with open(out_file) as f:
                assert "decode;fake-decode;" in f.read()

            # /mem + obsctl mem: one-shot ledger sample (no engines here —
            # buckets may be zero, but the endpoint and table must work)
            status, body = _get(e.url("/mem"))
            assert status == 200
            doc = json.loads(body)
            assert doc["sampled"] is True
            assert set(doc["buckets"]) == set(memledger.BUCKETS)
            assert obsctl.main(["mem", target]) == 0
            assert "bucket" in capsys.readouterr().out
    finally:
        stop.set()
        t.join(timeout=5)


def test_fleet_profile_merges_ranks(clean_planes):
    from paddlepaddle_tpu.distributed.store import TCPStore

    stop, t = _busy_decode_thread()
    try:
        prof = profiler.enable(start_thread=False)
        for _ in range(10):
            prof.sample_once()
        store = TCPStore("127.0.0.1", 0, is_master=True)
        for rank in (0, 1):
            aggregate.FleetPublisher(store, rank=rank, interval_s=60,
                                     text_fn=lambda: "").publish()
        doc = aggregate.collect_fleet_profile(store, world=2)
        assert set(doc["ranks"]) == {"0", "1"}
        merged = doc["merged"]
        # identical folded stacks sum across ranks: 2x the local count
        local = prof.categories(None).get("decode", 0)
        assert merged["categories"]["decode"] == 2 * local
        assert merged["top"][0]["category"] == "decode"
    finally:
        stop.set()
        t.join(timeout=5)


# ---------------------------------------------------------------------------
# memory ledger units
# ---------------------------------------------------------------------------

def test_memledger_leak_check_nonpaged_is_zero(clean_planes):
    class _Eng:
        kv_layout = "contiguous"

    assert memledger.leak_check(_Eng())["leaked_pages"] == 0


def test_memledger_sample_and_deltas(clean_planes):
    led = memledger.MemoryLedger(interval_s=5.0)
    s = led.sample()
    assert set(s["buckets"]) == set(memledger.BUCKETS)
    led.sample()
    j = led.jsonable()
    assert j["sampled"] is True
    assert set(j["deltas"]) == set(memledger.BUCKETS)
    # gauges rode the registry
    txt = obs.to_prometheus_text()
    assert 'paddle_mem_bytes{bucket="params"}' in txt
    assert "paddle_mem_leaked_pages" in txt


# ---------------------------------------------------------------------------
# default alert rules + perf_gate + flight dump satellites
# ---------------------------------------------------------------------------

def test_default_rules_grow_waste_burn_and_hbm_headroom():
    from paddlepaddle_tpu.observability.alerts import default_rules

    rules = {r.name: r for r in default_rules()}
    wb = rules["waste_burn"]
    assert wb.severity == "warn"
    assert {c.series for c in wb.conditions} == {"paddle_goodput_waste_pct"}
    assert {c.window_s for c in wb.conditions} == {60.0, 300.0}  # fast+slow
    hh = rules["hbm_headroom"]
    assert hh.severity == "page"
    assert [c.series for c in hh.conditions] == ["paddle_mem_headroom_ratio"]
    assert hh.conditions[0].op == "<"


def test_perf_gate_maps_goodput_fields():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(_REPO, "tools", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    HIGHER, LOWER, serving_metrics = pg.HIGHER, pg.LOWER, pg.serving_metrics
    m = serving_metrics({"serving_bench": {
        "goodput_tok_s": 123.4, "waste_pct": 2.5,
        "spec": {"goodput_tok_s": 150.0, "waste_pct": 20.0}}})
    assert m["serving.goodput_tok_s"] == (123.4, HIGHER)
    assert m["serving.waste_pct"] == (2.5, LOWER)
    assert m["serving.spec_goodput_tok_s"] == (150.0, HIGHER)
    assert m["serving.spec_waste_pct"] == (20.0, LOWER)


def test_flight_dump_carries_hot_stacks(clean_planes, tmp_path):
    stop, t = _busy_decode_thread()
    try:
        prof = profiler.enable(start_thread=False)
        for _ in range(12):
            prof.sample_once()
        flight.enable(str(tmp_path), install_hooks=False)
        path = flight.dump("profiler_test")
        recs = [json.loads(ln) for ln in open(path)]
        (hot,) = [r for r in recs if r["rec"] == "hot_stacks"]
        assert hot["hz"] == prof.hz
        assert hot["categories"].get("decode", 0) > 0
        assert hot["stacks"][0]["category"] == "decode"
    finally:
        stop.set()
        t.join(timeout=5)


# ---------------------------------------------------------------------------
# THE reconciliation drill: speculation + mid-flight hedge-loser cancel +
# hard stop — every decoded token attributed exactly once, zero leaked pages
# ---------------------------------------------------------------------------

def _llama(hidden=64, layers=2, vocab=128, max_len=96, dtype="bfloat16"):
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    return LlamaForCausalLM(LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 3,
        num_hidden_layers=layers, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=max_len,
        dtype=dtype))


def test_chaos_goodput_reconciles_exactly_with_spec_and_cancel(clean_planes):
    from paddlepaddle_tpu.inference import ServingEngine

    paddle.seed(0)
    target = _llama()
    paddle.seed(7)
    draft = _llama(hidden=32)   # weak independent draft: rejections happen

    rng = np.random.default_rng(3)
    eng = ServingEngine(target, max_batch_size=3, decode_chunk=8,
                        kv_page_size=16, draft=draft, spec_k=2)
    eng.start()
    inner = eng._engine
    # one request completes cleanly (useful tokens, trimmed retirement)
    eng.submit(rng.integers(0, 128, (5,)).astype(np.int32),
               max_new_tokens=6).result(300)
    # three long requests fill every slot
    futs = [eng.submit(rng.integers(0, 128, (p,)).astype(np.int32),
                       max_new_tokens=60)
            for p in (7, 11, 9)]
    deadline = time.time() + 120
    while inner.stats["tokens_out"] < 30 and time.time() < deadline:
        time.sleep(0.02)
    assert inner.stats["tokens_out"] >= 30, "engine never reached mid-decode"
    futs[0].cancel(reason="hedge_loser")     # a hedge twin won elsewhere
    before = inner.stats["tokens_out"]
    deadline = time.time() + 120
    while inner.stats["tokens_out"] < before + 10 and time.time() < deadline:
        time.sleep(0.01)
    eng.stop()           # abandons whatever is still mid-flight ("stop")

    snap = goodput.snapshot()
    # THE identity: every decoded token attributed to exactly one kind.
    # Not >=, not approximately — exactly.
    assert snap["decoded_tokens"] == inner.stats["tokens_out"], snap
    assert snap["kinds"]["useful"] > 0
    assert snap["kinds"]["hedge_loser"] > 0    # cancel reason threaded thru
    assert snap["kinds"]["stop"] > 0           # stop abandoned live slots
    assert snap["kinds"]["spec_rejected"] > 0  # weak draft was rejected
    assert snap["waste_pct"] > 0

    # zero leaked KV pages: pool used == slot-owned + prefix-pinned
    lk = memledger.leak_check(inner)
    assert lk["leaked_pages"] == 0, lk

    # the memory ledger attributes this engine's buckets
    s = memledger.MemoryLedger().sample()
    assert s["engines"] >= 1
    assert s["buckets"]["params"] > 0
    assert s["buckets"]["kv_pages"] > 0
    assert s["buckets"]["draft"] > 0
    assert s["leaked_pages"] == 0

    # the series are first-class on the registry
    txt = obs.to_prometheus_text()
    assert 'paddle_goodput_tokens_total{kind="useful"}' in txt
    assert "paddle_goodput_waste_pct" in txt
    assert 'paddle_mem_bytes{bucket="params"}' in txt

    # health() surfaces the block the bench/fleet sums
    gp = eng.health()["goodput"]
    assert gp["kinds"] == snap["kinds"]


def test_deadline_and_retry_reasons_reach_ledger(clean_planes):
    """The serving sweep threads distinct reasons through release_slot —
    unit-level, no real engine: release_slot's accounting is the single
    point remote/local cancels and deadline sweeps converge on."""
    from paddlepaddle_tpu.inference.serving import GenerationResult

    res = GenerationResult()
    assert res._cancel_kind == "cancel"        # disconnect-shaped default
    res.cancel(reason="hedge_loser")
    assert res._cancel_kind == "hedge_loser"
    assert res.cancelled()
