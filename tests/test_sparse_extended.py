"""Extended paddle.sparse surface (reference: python/paddle/sparse/ —
unary zero-preserving ops, binary ops, spmm/sddmm/mv/addmm, softmax,
transpose/reshape/coalesce, nn layer wrappers)."""

import numpy as np

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu import sparse as sp

D = np.array([[0, 2.0, 0], [3.0, 0, 4.0]], np.float32)


def _x():
    return paddle.to_tensor(D.copy()).to_sparse_coo()


def test_unary_zero_preserving():
    x = _x()
    mask = (D != 0)
    for name in ("sin", "tanh", "sqrt", "square", "log1p", "abs", "expm1",
                 "neg", "sign"):
        out = getattr(sp, name)(x)
        ref = getattr(np, {"neg": "negative"}.get(name, name))(D) * mask
        np.testing.assert_allclose(out.to_dense().numpy(), ref, rtol=1e-6,
                                   err_msg=name)
        assert sp.nnz(out) == sp.nnz(x)  # pattern preserved


def test_binary_and_matmul():
    x = _x()
    np.testing.assert_allclose(sp.add(x, x).to_dense().numpy(), 2 * D)
    np.testing.assert_allclose(sp.subtract(x, x).to_dense().numpy(), 0 * D)
    np.testing.assert_allclose(sp.multiply(x, x).numpy(), D * D)
    y = np.ones((3, 2), np.float32)
    np.testing.assert_allclose(sp.matmul(x, paddle.to_tensor(y)).numpy(), D @ y)
    v = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(sp.mv(x, v).numpy(), D @ v)
    i = np.ones((2, 2), np.float32)
    np.testing.assert_allclose(
        sp.addmm(paddle.to_tensor(i), x, paddle.to_tensor(y),
                 beta=0.5, alpha=2.0).numpy(), 0.5 * i + 2.0 * (D @ y))


def test_sddmm_and_mask_as():
    x = _x()
    a = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    b = np.random.default_rng(1).standard_normal((4, 3)).astype(np.float32)
    out = sp.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), x)
    np.testing.assert_allclose(out.to_dense().numpy(), (a @ b) * (D != 0),
                               rtol=1e-5)
    np.testing.assert_allclose(
        sp.mask_as(paddle.to_tensor(D * 3), x).to_dense().numpy(), 3 * D)


def test_softmax_rows():
    sm = sp.softmax(_x()).to_dense().numpy()
    np.testing.assert_allclose(sm[0, 1], 1.0)  # single-nnz row
    np.testing.assert_allclose(sm[1, 0] + sm[1, 2], 1.0)
    assert sm[0, 0] == sm[0, 2] == 0.0  # zeros stay zero


def test_layout_ops():
    x = _x()
    np.testing.assert_allclose(sp.transpose(x, [1, 0]).to_dense().numpy(), D.T)
    np.testing.assert_allclose(
        sp.reshape(x, [3, 2]).to_dense().numpy(), D.reshape(3, 2))
    np.testing.assert_allclose(sp.sum(x, axis=1).numpy(), D.sum(1))
    assert sp.nnz(sp.coalesce(x)) == 3
    assert sp.is_same_shape(x, _x())
    c = sp.cast(x, value_dtype="float64")
    assert str(c.dtype) == "float64"


def test_csr_roundtrip_and_nn():
    crows = np.array([0, 1, 3])
    cols = np.array([1, 0, 2])
    vals = np.array([2.0, 3.0, 4.0], np.float32)
    x = sp.sparse_csr_tensor(crows, cols, vals, shape=[2, 3])
    np.testing.assert_allclose(x.to_dense().numpy(), D)
    out = sp.nn.ReLU()(x)
    np.testing.assert_allclose(out.to_dense().numpy(), np.maximum(D, 0))
    out6 = sp.nn.ReLU6()(sp.scale(x, 4.0))
    assert out6.to_dense().numpy().max() <= 6.0


# --------------------------------------------------------------------------
# CSR format (round 3): real BCSR storage, COO interop, attention
# --------------------------------------------------------------------------


def test_csr_roundtrip_and_accessors():
    import numpy as np

    import paddlepaddle_tpu as paddle
    import paddlepaddle_tpu.sparse as sp

    dense = np.array([[1., 0, 2], [0, 0, 3], [4, 5, 0]], np.float32)
    t = sp.sparse_csr_tensor([0, 2, 3, 5], [0, 2, 2, 0, 1],
                             [1., 2., 3., 4., 5.], shape=[3, 3])
    assert t.is_sparse_csr() and not t.is_sparse_coo()
    np.testing.assert_allclose(t.to_dense().numpy(), dense)
    np.testing.assert_array_equal(t.crows().numpy(), [0, 2, 3, 5])
    np.testing.assert_array_equal(t.cols().numpy(), [0, 2, 2, 0, 1])
    np.testing.assert_allclose(t.values().numpy(), [1., 2., 3., 4., 5.])
    # dense -> csr -> coo -> csr
    t2 = paddle.to_tensor(dense).to_sparse_csr()
    assert t2.is_sparse_csr()
    coo = t2.to_sparse_coo()
    assert coo.is_sparse_coo()
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(back.to_dense().numpy(), dense)
    assert sp.nnz(back) == 5


def test_csr_ops_preserve_format_and_match_dense():
    import numpy as np

    import paddlepaddle_tpu as paddle
    import paddlepaddle_tpu.sparse as sp

    rng = np.random.default_rng(0)
    dense = rng.standard_normal((4, 5)).astype(np.float32)
    dense[rng.random((4, 5)) < 0.5] = 0.0
    t = paddle.to_tensor(dense).to_sparse_csr()
    # zero-preserving unary keeps CSR and matches dense
    out = sp.sin(t)
    assert out.is_sparse_csr()
    np.testing.assert_allclose(out.to_dense().numpy(), np.sin(dense),
                               rtol=1e-6, atol=1e-6)
    # spmm vs dense
    w = rng.standard_normal((5, 3)).astype(np.float32)
    np.testing.assert_allclose(sp.matmul(t, w).numpy(), dense @ w,
                               rtol=1e-5, atol=1e-5)
    # sparse softmax vs dense row-softmax over the nnz pattern
    sm = sp.softmax(t)
    assert sm.is_sparse_csr()
    ref = np.zeros_like(dense)
    for i in range(dense.shape[0]):
        nz = dense[i] != 0
        if nz.any():
            e = np.exp(dense[i][nz] - dense[i][nz].max())
            ref[i][nz] = e / e.sum()
    np.testing.assert_allclose(sm.to_dense().numpy(), ref, rtol=1e-5,
                               atol=1e-6)


def test_sparse_attention_matches_dense_masked():
    import numpy as np

    import paddlepaddle_tpu as paddle
    import paddlepaddle_tpu.sparse as sp

    rng = np.random.default_rng(1)
    s, d = 8, 16
    q = rng.standard_normal((s, d)).astype(np.float32)
    k = rng.standard_normal((s, d)).astype(np.float32)
    v = rng.standard_normal((s, d)).astype(np.float32)
    mask = np.tril(np.ones((s, s), np.float32))  # causal pattern
    mcsr = paddle.to_tensor(mask).to_sparse_csr()
    out = sp.nn.functional.attention(q, k, v, mcsr).numpy()
    logits = (q @ k.T) / np.sqrt(d)
    logits[mask == 0] = -1e30
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, p @ v, rtol=1e-4, atol=1e-5)

    # batched [b, h, s, d]
    qb = rng.standard_normal((2, 2, s, d)).astype(np.float32)
    kb = rng.standard_normal((2, 2, s, d)).astype(np.float32)
    vb = rng.standard_normal((2, 2, s, d)).astype(np.float32)
    outb = sp.nn.functional.attention(qb, kb, vb, mcsr).numpy()
    assert outb.shape == (2, 2, s, d)
    lb = np.einsum("bhsd,bhtd->bhst", qb, kb) / np.sqrt(d)
    lb[..., mask == 0] = -1e30
    pb = np.exp(lb - lb.max(-1, keepdims=True))
    pb /= pb.sum(-1, keepdims=True)
    np.testing.assert_allclose(outb, np.einsum("bhst,bhtd->bhsd", pb, vb),
                               rtol=1e-4, atol=1e-5)


def test_sparse_attention_key_padding_and_attn_mask():
    import numpy as np

    import paddlepaddle_tpu as paddle
    import paddlepaddle_tpu.sparse as sp

    rng = np.random.default_rng(3)
    s, d = 6, 8
    q = rng.standard_normal((s, d)).astype(np.float32)
    k = rng.standard_normal((s, d)).astype(np.float32)
    v = rng.standard_normal((s, d)).astype(np.float32)
    mask = np.ones((s, s), np.float32)
    mcsr = paddle.to_tensor(mask).to_sparse_csr()
    kpm = np.zeros((s,), np.float32)
    kpm[-2:] = 1.0  # last two keys padded
    am = rng.standard_normal((s, s)).astype(np.float32)
    out = sp.nn.functional.attention(q, k, v, mcsr, key_padding_mask=kpm,
                                     attn_mask=am).numpy()
    logits = (q @ k.T) / np.sqrt(d) + am
    logits[:, kpm.astype(bool)] = -1e30
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, p @ v, rtol=1e-4, atol=1e-5)


def test_sparse_attention_3d_mask_per_head():
    """Reference contract (round-3 advisor, medium): 3-D CSR mask of dense
    shape [batch*heads, seq, seq] — each (batch, head) slice carries its OWN
    sparsity pattern (python/paddle/sparse/nn/functional/transformer.py)."""
    import numpy as np

    import paddlepaddle_tpu as paddle
    import paddlepaddle_tpu.sparse as sp

    rng = np.random.default_rng(7)
    b, h, s, d = 2, 2, 8, 16
    q = rng.standard_normal((b, h, s, d)).astype(np.float32)
    k = rng.standard_normal((b, h, s, d)).astype(np.float32)
    v = rng.standard_normal((b, h, s, d)).astype(np.float32)
    # distinct random pattern per (batch, head); every row keeps >=1 key
    masks = (rng.random((b * h, s, s)) < 0.5).astype(np.float32)
    masks[:, np.arange(s), np.arange(s)] = 1.0
    mcsr = paddle.to_tensor(masks).to_sparse_csr()
    out = sp.nn.functional.attention(q, k, v, mcsr).numpy()
    lb = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(d)
    lb[masks.reshape(b, h, s, s) == 0] = -1e30
    p = np.exp(lb - lb.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, np.einsum("bhst,bhtd->bhsd", p, v),
                               rtol=1e-4, atol=1e-5)

    # wrong leading dim must raise, not silently misread indices
    bad = paddle.to_tensor(masks[: b * h - 1]).to_sparse_csr()
    try:
        sp.nn.functional.attention(q, k, v, bad)
        raise AssertionError("expected ValueError for mismatched mask dim")
    except ValueError:
        pass
