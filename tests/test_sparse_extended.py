"""Extended paddle.sparse surface (reference: python/paddle/sparse/ —
unary zero-preserving ops, binary ops, spmm/sddmm/mv/addmm, softmax,
transpose/reshape/coalesce, nn layer wrappers)."""

import numpy as np

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu import sparse as sp

D = np.array([[0, 2.0, 0], [3.0, 0, 4.0]], np.float32)


def _x():
    return paddle.to_tensor(D.copy()).to_sparse_coo()


def test_unary_zero_preserving():
    x = _x()
    mask = (D != 0)
    for name in ("sin", "tanh", "sqrt", "square", "log1p", "abs", "expm1",
                 "neg", "sign"):
        out = getattr(sp, name)(x)
        ref = getattr(np, {"neg": "negative"}.get(name, name))(D) * mask
        np.testing.assert_allclose(out.to_dense().numpy(), ref, rtol=1e-6,
                                   err_msg=name)
        assert sp.nnz(out) == sp.nnz(x)  # pattern preserved


def test_binary_and_matmul():
    x = _x()
    np.testing.assert_allclose(sp.add(x, x).to_dense().numpy(), 2 * D)
    np.testing.assert_allclose(sp.subtract(x, x).to_dense().numpy(), 0 * D)
    np.testing.assert_allclose(sp.multiply(x, x).numpy(), D * D)
    y = np.ones((3, 2), np.float32)
    np.testing.assert_allclose(sp.matmul(x, paddle.to_tensor(y)).numpy(), D @ y)
    v = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(sp.mv(x, v).numpy(), D @ v)
    i = np.ones((2, 2), np.float32)
    np.testing.assert_allclose(
        sp.addmm(paddle.to_tensor(i), x, paddle.to_tensor(y),
                 beta=0.5, alpha=2.0).numpy(), 0.5 * i + 2.0 * (D @ y))


def test_sddmm_and_mask_as():
    x = _x()
    a = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    b = np.random.default_rng(1).standard_normal((4, 3)).astype(np.float32)
    out = sp.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), x)
    np.testing.assert_allclose(out.to_dense().numpy(), (a @ b) * (D != 0),
                               rtol=1e-5)
    np.testing.assert_allclose(
        sp.mask_as(paddle.to_tensor(D * 3), x).to_dense().numpy(), 3 * D)


def test_softmax_rows():
    sm = sp.softmax(_x()).to_dense().numpy()
    np.testing.assert_allclose(sm[0, 1], 1.0)  # single-nnz row
    np.testing.assert_allclose(sm[1, 0] + sm[1, 2], 1.0)
    assert sm[0, 0] == sm[0, 2] == 0.0  # zeros stay zero


def test_layout_ops():
    x = _x()
    np.testing.assert_allclose(sp.transpose(x, [1, 0]).to_dense().numpy(), D.T)
    np.testing.assert_allclose(
        sp.reshape(x, [3, 2]).to_dense().numpy(), D.reshape(3, 2))
    np.testing.assert_allclose(sp.sum(x, axis=1).numpy(), D.sum(1))
    assert sp.nnz(sp.coalesce(x)) == 3
    assert sp.is_same_shape(x, _x())
    c = sp.cast(x, value_dtype="float64")
    assert str(c.dtype) == "float64"


def test_csr_roundtrip_and_nn():
    crows = np.array([0, 1, 3])
    cols = np.array([1, 0, 2])
    vals = np.array([2.0, 3.0, 4.0], np.float32)
    x = sp.sparse_csr_tensor(crows, cols, vals, shape=[2, 3])
    np.testing.assert_allclose(x.to_dense().numpy(), D)
    out = sp.nn.ReLU()(x)
    np.testing.assert_allclose(out.to_dense().numpy(), np.maximum(D, 0))
    out6 = sp.nn.ReLU6()(sp.scale(x, 4.0))
    assert out6.to_dense().numpy().max() <= 6.0
