"""End-to-end context-parallel Llama: ring attention inside ShardedTrainStep."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.distributed.mesh import ProcessMesh, set_mesh
from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM, llama_sharding_rules


def test_llama_context_parallel_train():
    import jax

    from paddlepaddle_tpu.optimizer import AdamW
    from paddlepaddle_tpu.parallel import ShardedTrainStep

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    cfg = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4,
                           kv_heads=4, max_len=64)
    cfg.context_parallel_axis = "sp"
    mesh = ProcessMesh(shape=[2, 4], dim_names=["dp", "sp"])
    set_mesh(mesh)
    m = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
    step = ShardedTrainStep(m, opt, lambda mm, ids, labels: mm(ids, labels=labels),
                            mesh=mesh, rules=llama_sharding_rules(),
                            data_axes=("dp",), seq_axis="sp")
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (4, 32)).astype(np.int32)
    losses = [float(step(ids, ids).numpy()) for _ in range(5)]
    assert losses[-1] < losses[0]
    set_mesh(None)


def test_cp_loss_matches_dense_llama():
    """Same weights: context-parallel forward == dense forward."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    paddle.seed(11)
    cfg = LlamaConfig.tiny(vocab_size=32, hidden_size=32, layers=1, heads=4,
                           kv_heads=4, max_len=32)
    m = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, 32, (2, 16)).astype(np.int32)
    dense_loss = float(m(ids, labels=ids).numpy())

    mesh = ProcessMesh(shape=[1, 4], dim_names=["dp", "sp"])
    set_mesh(mesh)
    cfg.context_parallel_axis = "sp"
    loss_cp = float(m(ids, labels=ids).numpy())
    set_mesh(None)
    cfg.context_parallel_axis = None
    assert abs(dense_loss - loss_cp) < 1e-3, (dense_loss, loss_cp)
