"""Fast-tier smoke coverage for the modules whose full suites are marked
slow: every package keeps at least one sub-minute end-to-end exercise in
``-m "not slow"`` runs (the tier contract in pytest.ini / README)."""

import numpy as np

import paddlepaddle_tpu as paddle


def test_llama_tiny_forward_loss():
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    m = LlamaForCausalLM(LlamaConfig.tiny(vocab_size=64, hidden_size=32,
                                          layers=1, heads=2, kv_heads=1,
                                          max_len=16))
    ids = np.random.default_rng(0).integers(0, 64, (2, 8)).astype(np.int32)
    loss = m(ids, labels=ids)
    assert np.isfinite(float(loss.numpy()))


def test_moe_layer_tiny_forward():
    from paddlepaddle_tpu.parallel.moe import MoELayer

    m = MoELayer(d_model=8, d_hidden=16, num_experts=2)
    y = m(np.random.default_rng(0).standard_normal((1, 4, 8)).astype(np.float32))
    assert y.shape == [1, 4, 8]
    assert np.isfinite(float(m.l_aux.numpy()))


def test_hybrid_block_tiny():
    import jax
    import jax.numpy as jnp

    from paddlepaddle_tpu.parallel.hybrid import (HybridStageConfig,
                                                  init_llama_stage,
                                                  make_llama_block)

    cfg = HybridStageConfig(hidden_size=16, intermediate_size=32, num_heads=2,
                            num_kv_heads=1, layers_per_stage=1, vocab_size=32,
                            max_seq_len=8)
    sp = init_llama_stage(cfg, jax.random.PRNGKey(0))
    block = make_llama_block(cfg, tp_axis=None, fsdp_axis=None, remat=False)
    x = jnp.ones((1, 8, 16), jnp.float32)
    out = block(sp, x)
    assert out.shape == x.shape and bool(jnp.isfinite(out).all())


def test_hapi_model_fit_one_epoch():
    import paddlepaddle_tpu.nn as nn
    from paddlepaddle_tpu.hapi.model import Model

    net = nn.Linear(4, 2)
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    x = np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 2, (8, 1)).astype(np.int64)
    hist = m.fit([( x, y )], epochs=1, verbose=0)
    assert hist and "loss" in hist[0]


def test_lbfgs_quadratic():
    from paddlepaddle_tpu.optimizer import LBFGS

    w = paddle.to_tensor(np.asarray([3.0, -2.0], np.float32),
                         stop_gradient=False)
    opt = LBFGS(learning_rate=1.0, parameters=[w], max_iter=8)

    def closure():
        opt.clear_grad()
        loss = ((w - 1.0) ** 2).sum()
        loss.backward()
        return loss

    for _ in range(3):
        opt.step(closure)
    np.testing.assert_allclose(w.numpy(), [1.0, 1.0], atol=1e-3)


def test_sharded_train_step_tiny_mesh():
    import jax

    from paddlepaddle_tpu.distributed.mesh import ProcessMesh
    from paddlepaddle_tpu.optimizer import SGD
    from paddlepaddle_tpu.parallel import ShardedTrainStep

    if len(jax.devices()) < 2:
        return
    mesh = ProcessMesh(shape=[2], dim_names=["dp"])
    net = paddle.nn.Linear(4, 4)
    opt = SGD(learning_rate=0.1, parameters=net.parameters())
    step = ShardedTrainStep(
        net, opt, loss_fn=lambda m, x, y: ((m(x) - y) ** 2).mean(),
        mesh=mesh, rules=[(r".*", ())], data_axes=("dp",))
    x = np.ones((4, 4), np.float32)
    loss = step(x, x)
    assert np.isfinite(float(loss.numpy()))
