"""nn long-tail surface: RNN stack vs torch, losses, pools, decode helpers,
and namespace closure against the reference nn / nn.functional exports."""

import numpy as np
import pytest
import torch

import paddlepaddle_tpu as paddle
import paddlepaddle_tpu.nn.functional as F

rng = np.random.default_rng(0)


def _copy_l0(pcell, tmod, suffix=""):
    pcell.weight_ih.set_value(getattr(tmod, "weight_ih_l0" + suffix).detach().numpy())
    pcell.weight_hh.set_value(getattr(tmod, "weight_hh_l0" + suffix).detach().numpy())
    pcell.bias_ih.set_value(getattr(tmod, "bias_ih_l0" + suffix).detach().numpy())
    pcell.bias_hh.set_value(getattr(tmod, "bias_hh_l0" + suffix).detach().numpy())


def test_lstm_matches_torch():
    x = rng.standard_normal((2, 5, 3)).astype(np.float32)
    tl = torch.nn.LSTM(3, 4, batch_first=True)
    pl = paddle.nn.LSTM(3, 4)
    _copy_l0(pl.cell_fw_l0, tl)
    ty, (th, tc) = tl(torch.tensor(x))
    py, (ph, pc) = pl(x)
    np.testing.assert_allclose(py.numpy(), ty.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(ph.numpy(), th.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(pc.numpy(), tc.detach().numpy(), atol=1e-5)


def test_gru_bidirectional_matches_torch():
    x = rng.standard_normal((2, 5, 3)).astype(np.float32)
    tg = torch.nn.GRU(3, 4, batch_first=True, bidirectional=True)
    pg = paddle.nn.GRU(3, 4, direction="bidirect")
    _copy_l0(pg.cell_fw_l0, tg)
    _copy_l0(pg.cell_bw_l0, tg, "_reverse")
    ty, th = tg(torch.tensor(x))
    py, ph = pg(x)
    np.testing.assert_allclose(py.numpy(), ty.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(ph.numpy(), th.detach().numpy(), atol=1e-5)


def test_rnn_sequence_length_masking():
    cell = paddle.nn.SimpleRNNCell(3, 4)
    rnn = paddle.nn.RNN(cell)
    x = rng.standard_normal((2, 5, 3)).astype(np.float32)
    y, h = rnn(paddle.to_tensor(x),
               sequence_length=np.array([3, 5], np.int64))
    y_full, _ = rnn(paddle.to_tensor(x))
    # sequence 0 freezes after t=3; sequence 1 matches the unmasked run
    np.testing.assert_allclose(y.numpy()[1], y_full.numpy()[1], atol=1e-6)
    np.testing.assert_allclose(h.numpy()[0], y.numpy()[0, 2], atol=1e-6)


def test_lstm_trains():
    lstm = paddle.nn.LSTM(3, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=lstm.parameters())
    x = rng.standard_normal((2, 5, 3)).astype(np.float32)
    first = last = None
    for _ in range(5):
        y, _ = lstm(x)
        loss = (y ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss.numpy())
        last = float(loss.numpy())
    assert last < first


def test_losses_match_torch():
    x = rng.standard_normal((4, 5)).astype(np.float32)
    y = rng.integers(0, 5, (4,)).astype(np.int64)
    np.testing.assert_allclose(
        F.multi_margin_loss(x, y).numpy(),
        torch.nn.functional.multi_margin_loss(
            torch.tensor(x), torch.tensor(y)).numpy(), rtol=1e-5)

    t = rng.standard_normal((4, 5)).astype(np.float32)
    sign = np.sign(rng.standard_normal((4, 5))).astype(np.float32)
    np.testing.assert_allclose(
        F.soft_margin_loss(x, sign).numpy(),
        torch.nn.functional.soft_margin_loss(
            torch.tensor(x), torch.tensor(sign)).numpy(), rtol=1e-5)

    var = np.abs(rng.standard_normal((4, 5))).astype(np.float32) + 0.1
    np.testing.assert_allclose(
        F.gaussian_nll_loss(x, t, var).numpy(),
        torch.nn.functional.gaussian_nll_loss(
            torch.tensor(x), torch.tensor(t), torch.tensor(var)).numpy(),
        rtol=1e-4)

    np.testing.assert_allclose(
        F.poisson_nll_loss(x, np.abs(t)).numpy(),
        torch.nn.functional.poisson_nll_loss(
            torch.tensor(x), torch.tensor(np.abs(t))).numpy(), rtol=1e-5)

    lab01 = (rng.standard_normal((4, 5)) > 0).astype(np.float32)
    np.testing.assert_allclose(
        F.multi_label_soft_margin_loss(x, lab01).numpy(),
        torch.nn.functional.multilabel_soft_margin_loss(
            torch.tensor(x), torch.tensor(lab01)).numpy(), rtol=1e-5)

    a, p, n = (rng.standard_normal((4, 8)).astype(np.float32)
               for _ in range(3))
    np.testing.assert_allclose(
        F.triplet_margin_with_distance_loss(a, p, n).numpy(),
        torch.nn.functional.triplet_margin_loss(
            torch.tensor(a), torch.tensor(p), torch.tensor(n)).numpy(),
        rtol=1e-4)


def test_unpool_roundtrip():
    x = paddle.to_tensor(rng.standard_normal((1, 2, 8)).astype(np.float32))
    pooled, idx = F.max_pool1d(x, 2, return_mask=True)
    restored = F.max_unpool1d(pooled, idx, 2).numpy()
    # restored has pooled maxima at their argmax positions, zeros elsewhere
    assert restored.shape == (1, 2, 8)
    np.testing.assert_allclose(np.sort(restored[restored != 0]),
                               np.sort(pooled.numpy().ravel()))

    x2 = paddle.to_tensor(rng.standard_normal((1, 1, 4, 4)).astype(np.float32))
    p2, i2 = F.max_pool2d(x2, 2, return_mask=True)
    r2 = F.max_unpool2d(p2, i2, 2)
    t2 = torch.nn.functional.max_unpool2d(
        torch.tensor(p2.numpy()), torch.tensor(i2.numpy()), 2).numpy()
    np.testing.assert_allclose(r2.numpy(), t2)


def test_lp_pool_matches_torch():
    x = rng.standard_normal((1, 2, 8)).astype(np.float32)
    np.testing.assert_allclose(
        F.lp_pool1d(x, 2.0, 2).numpy(),
        torch.nn.functional.lp_pool1d(torch.tensor(x), 2.0, 2).numpy(),
        rtol=1e-4, atol=1e-5)


def test_shuffles_and_pads():
    x = rng.standard_normal((1, 4, 4, 4)).astype(np.float32)
    pu = paddle.nn.PixelUnshuffle(2)(x)
    tu = torch.nn.functional.pixel_unshuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(pu.numpy(), tu)
    z = paddle.nn.ZeroPad2D([1, 1, 2, 2])(x)
    assert z.shape == [1, 4, 8, 6]
    uf = paddle.nn.Unflatten(1, [2, 2])(x)
    assert uf.shape == [1, 2, 2, 4, 4]
    s2d = paddle.nn.Softmax2D()(x)
    np.testing.assert_allclose(np.asarray(s2d.numpy()).sum(1),
                               np.ones((1, 4, 4)), rtol=1e-5)


def test_qkvpacked_and_flashmask():
    qkv = rng.standard_normal((2, 6, 3, 2, 8)).astype(np.float32)
    out, _ = F.flash_attn_qkvpacked(qkv, causal=True)
    ref = F.scaled_dot_product_attention(qkv[:, :, 0], qkv[:, :, 1],
                                         qkv[:, :, 2], is_causal=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)


def test_beam_search_decodes():
    """A toy cell that deterministically prefers token (prev+1) mod V."""
    V = 5

    class Cell:
        def __call__(self, tokens, states):
            import jax.numpy as jnp

            from paddlepaddle_tpu.core.dispatch import unwrap, wrap

            tok = np.asarray(unwrap(tokens)).reshape(-1)
            logits = np.full((len(tok), V), -5.0, np.float32)
            logits[np.arange(len(tok)), (tok + 1) % V] = 5.0
            return wrap(np.asarray(logits)), states

    from paddlepaddle_tpu.nn import BeamSearchDecoder, dynamic_decode

    dec = BeamSearchDecoder(Cell(), start_token=np.zeros((1,), np.int64),
                            end_token=4, beam_size=2)
    seqs, scores = dynamic_decode(dec, max_step_num=6)
    top = seqs.numpy()[0, 0]
    assert list(top[:4]) == [1, 2, 3, 4]  # follows the chain to EOS


def test_gather_tree():
    ids = np.array([[[2, 5]], [[6, 1]], [[3, 9]]], np.int64)      # [T=3,B=1,b=2]
    parents = np.array([[[0, 0]], [[1, 0]], [[1, 0]]], np.int64)
    out = F.gather_tree(ids, parents).numpy()
    # beam 0 at t=2 came from parent 1 at t=1 (token 1), which came from
    # parent 0 at t=0 (token 2)
    assert list(out[:, 0, 0]) == [2, 1, 3]


def test_margin_cross_entropy_and_rnnt():
    # arcface margin: with margins zeroed it equals plain scaled CE
    feats = rng.standard_normal((4, 6)).astype(np.float32)
    feats /= np.linalg.norm(feats, axis=1, keepdims=True)
    y = rng.integers(0, 6, (4,)).astype(np.int64)
    ours = F.margin_cross_entropy(feats, y, margin1=1.0, margin2=0.0,
                                  margin3=0.0, scale=4.0).numpy()
    ref = torch.nn.functional.cross_entropy(torch.tensor(feats * 4.0),
                                            torch.tensor(y)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_rnnt_loss_matches_torchaudio():
    ta = pytest.importorskip("torchaudio")
    B, T, U1, V = 2, 4, 3, 5
    logits = rng.standard_normal((B, T, U1, V)).astype(np.float32)
    labels = rng.integers(1, V, (B, U1 - 1)).astype(np.int32)
    ilen = np.array([4, 3], np.int32)
    llen = np.array([2, 1], np.int32)
    ours = F.rnnt_loss(logits, labels, ilen, llen, blank=0,
                       reduction="none").numpy()
    ref = ta.functional.rnnt_loss(
        torch.tensor(logits), torch.tensor(labels), torch.tensor(ilen),
        torch.tensor(llen), blank=0, reduction="none").numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_reference_nn_namespace_closed():
    import os
    import re

    if not os.path.exists("/root/reference"):
        pytest.skip("reference tree not present")
    for path, mod in [("/root/reference/python/paddle/nn/__init__.py",
                       paddle.nn),
                      ("/root/reference/python/paddle/nn/functional/__init__.py",
                       paddle.nn.functional)]:
        ref = set(re.findall(r"'(\w+)'", open(path).read()))
        missing = sorted(n for n in ref
                         if not hasattr(mod, n) and not n.startswith("_"))
        assert missing == [], f"{path}: missing {missing}"
