"""Net-chaos proxy (resilience/netchaos.py) + the wire-hardening paths
it exists to exercise: the client's stream-progress watchdog
(ReplicaStalledError in ~heartbeat_timeout_s, not read_timeout_s), frame
CRC verification (WireCorruptionError, never silently-wrong tokens), and
the typed-vs-untyped split the router's failover depends on.

Budget discipline: everything here runs against a scripted in-process
FAKE frame server (no engine, no subprocess) — the whole module is
seconds-cheap. The real-process drills live behind ``chaos`` markers in
tools/run_chaos.sh.
"""

import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from paddlepaddle_tpu.inference.c_api_server import (
    _MAGIC,
    _OP_SUBMIT,
    _ST_CHUNK,
    _ST_OK,
    _pack_tensor,
    crc_wrap,
)
from paddlepaddle_tpu.inference.remote_replica import RemoteReplicaClient
from paddlepaddle_tpu.inference.robustness import (
    ReplicaStalledError,
    ServingError,
    WireCorruptionError,
)
from paddlepaddle_tpu.resilience.netchaos import (
    NETCHAOS_MODES,
    NETCHAOS_POINTS,
    NetChaosProxy,
    env_seed,
    parse_netchaos,
)


# -- spec grammar (no sockets) ------------------------------------------------

def test_parse_netchaos_fields_and_schedules():
    specs = parse_netchaos(
        "down:blackhole:@2; up:delay:0.5:80, conn:reset:%3")
    assert [(s.point, s.mode) for s in specs] == [
        ("down", "blackhole"), ("up", "delay"), ("conn", "reset")]
    bh, dl, rst = specs
    assert (bh.sched_kind, bh.sched_value) == ("at", 2)
    assert (dl.sched_kind, dl.sched_value) == ("prob", 0.5)
    assert dl.arg == 80
    assert (rst.sched_kind, rst.sched_value) == ("every", 3)


def test_parse_netchaos_rejects_typos_loudly():
    with pytest.raises(ValueError, match="point"):
        parse_netchaos("sideways:delay:1.0")
    with pytest.raises(ValueError, match="mode"):
        parse_netchaos("down:gremlins:1.0")
    with pytest.raises(ValueError, match="sched"):
        parse_netchaos("down:delay")
    assert parse_netchaos("") == []


def test_env_seed_falls_back_to_chaos_seed(monkeypatch):
    monkeypatch.delenv("PADDLE_NETCHAOS_SEED", raising=False)
    monkeypatch.setenv("PADDLE_CHAOS_SEED", "41")
    assert env_seed() == 41
    monkeypatch.setenv("PADDLE_NETCHAOS_SEED", "7")
    assert env_seed() == 7
    monkeypatch.setenv("PADDLE_NETCHAOS_SEED", "nope")
    assert env_seed() == 0


# -- scripted fake frame server ----------------------------------------------

def _chunk(ev, crc=False, **kw):
    blob = json.dumps(dict({"ev": ev}, **kw)).encode()
    f = (struct.pack("<IB", _MAGIC, _ST_CHUNK)
         + struct.pack("<I", len(blob)) + blob)
    return crc_wrap(f) if crc else f


def _terminal(out, crc=False):
    arr = np.ascontiguousarray(np.asarray(out, np.int32))
    blob = json.dumps({"n_new": int(arr.size), "n_at_first": 1,
                       "streaming": True}).encode()
    f = (struct.pack("<IB", _MAGIC, _ST_OK)
         + struct.pack("<I", len(blob)) + blob
         + _pack_tensor("output_ids", arr))
    return crc_wrap(f) if crc else f


class FakeWire:
    """Loopback TCP server speaking just enough of the C-API frame
    protocol to drive RemoteReplicaClient's stream reader — each
    connection reads ONE request frame, then plays ``script``: a list of
    frame bytes, ``("sleep", s)`` pauses, or ``"hang"`` (go silent with
    the socket open — what a black-holed peer looks like from userspace).
    """

    def __init__(self, script):
        self.script = script
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(8)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            head = b""
            while len(head) < 8:
                b = conn.recv(8 - len(head))
                if not b:
                    return
                head += b
            (n,) = struct.unpack("<Q", head)
            body = b""
            while len(body) < n:
                b = conn.recv(n - len(body))
                if not b:
                    return
                body += b
            for step in self.script:
                if step == "hang":
                    self._stop.wait(30.0)
                    return
                if isinstance(step, tuple) and step[0] == "sleep":
                    time.sleep(step[1])
                    continue
                conn.sendall(struct.pack("<Q", len(step)) + step)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def _client(target_port, proxy=None, **kw):
    kw.setdefault("heartbeat_timeout_s", 0.6)
    kw.setdefault("read_timeout_s", 5.0)
    kw.setdefault("connect_timeout_s", 2.0)
    cli = RemoteReplicaClient(address=target_port, name="fake", **kw)
    if proxy is not None:
        cli._nc_proxy = proxy       # dial the chaos proxy, not the target
    return cli


OUT = np.arange(12, dtype=np.int32)
HAPPY = [_chunk("accepted"), _chunk("first", n=1), _terminal(OUT)]


@pytest.fixture
def wire():
    servers = []

    def make(script):
        w = FakeWire(script)
        servers.append(w)
        return w

    yield make
    for w in servers:
        w.close()


# -- proxy behavior through the real client ----------------------------------

def test_proxy_is_transparent_when_nothing_fires(wire):
    w = wire(HAPPY)
    with NetChaosProxy(w.port, specs="down:delay:@9999", seed=0) as px:
        fut = _client(w.port, px).submit([1, 2, 3], max_new_tokens=4)
        np.testing.assert_array_equal(fut.result(timeout=5.0), OUT)
    assert px.fire_counts() == {}
    # frame-aware hit accounting: every down frame crossed the seam
    assert px.hit_counts().get("down", 0) >= 3


def test_delay_fires_on_every_frame_and_stream_survives(wire):
    w = wire(HAPPY)
    with NetChaosProxy(w.port, specs="down:delay:1.0:20", seed=0) as px:
        fut = _client(w.port, px).submit([1], max_new_tokens=4)
        np.testing.assert_array_equal(fut.result(timeout=5.0), OUT)
    assert px.fire_counts().get("down", 0) >= 3


def test_blackhole_mid_stream_trips_stall_watchdog_fast(wire):
    """The acceptance drill in miniature: frame 1 (accepted) passes, the
    wire then black-holes — the client must surface a TYPED retryable
    ReplicaStalledError within ~heartbeat_timeout_s, not read_timeout_s,
    and never a wrong/partial result."""
    w = wire(HAPPY + ["hang"])
    with NetChaosProxy(w.port, specs="down:blackhole:@2", seed=0) as px:
        cli = _client(w.port, px, heartbeat_timeout_s=0.6)
        t0 = time.perf_counter()
        fut = cli.submit([1], max_new_tokens=4)
        with pytest.raises(ReplicaStalledError) as ei:
            fut.result(timeout=5.0)
        took = time.perf_counter() - t0
    assert took < 3.0, f"stall took {took:.2f}s — watchdog not bounding"
    assert ei.value.stalled_after_s == pytest.approx(0.6)
    assert isinstance(ei.value, ServingError)     # router-retryable shape
    assert px.fire_counts().get("down") == 1


def test_conn_blackhole_stalls_submit_synchronously(wire):
    w = wire(HAPPY)
    with NetChaosProxy(w.port, specs="conn:blackhole:1.0", seed=0) as px:
        cli = _client(w.port, px, heartbeat_timeout_s=0.5,
                      read_timeout_s=5.0)
        with pytest.raises(ReplicaStalledError):
            cli.submit([1], max_new_tokens=4)
    assert px.fire_counts().get("conn", 0) >= 1


def test_corrupt_with_crc_surfaces_wire_corruption_never_bad_tokens(wire):
    """Corruption lands past the CRC header → the client must fail TYPED
    (WireCorruptionError, retryable) — the pre-CRC failure mode was
    silently wrong output_ids."""
    w = wire([_chunk("accepted", crc=True), _chunk("first", n=1, crc=True),
              _terminal(OUT, crc=True)])
    with NetChaosProxy(w.port, specs="down:corrupt:@3", seed=3) as px:
        fut = _client(w.port, px).submit([1], max_new_tokens=4)
        with pytest.raises(WireCorruptionError):
            fut.result(timeout=5.0)
    assert px.fire_counts().get("down") == 1


def test_corruption_without_crc_would_pass_silently(wire):
    """Contrast pin for the test above: the SAME corrupted terminal frame
    without CRC protection decodes 'successfully' into wrong bytes — this
    is the failure class the CRC flag exists to kill. (If this test ever
    fails because corruption happens to break JSON/tensor parsing, tighten
    the corrupt offset — the point is that no check CATCHES it.)"""
    w = wire([_chunk("accepted"), _terminal(OUT)])
    with NetChaosProxy(w.port, specs="down:corrupt:@2", seed=3) as px:
        fut = _client(w.port, px, crc=False).submit([1], max_new_tokens=4)
        try:
            out = fut.result(timeout=5.0)
            assert not np.array_equal(out, OUT)   # wrong tokens, no error
        except (WireCorruptionError,) as e:       # pragma: no cover
            pytest.fail(f"no CRC on the wire yet {e!r} was raised")
        except Exception:
            pass   # parse desync is also acceptable evidence of damage
    assert px.fire_counts().get("down") == 1


def test_reset_mid_stream_is_untyped_connection_error(wire):
    """RST → ConnectionError (UNTYPED) — the router's failover class,
    distinct from the stall/corruption typed retryables."""
    w = wire(HAPPY)
    with NetChaosProxy(w.port, specs="down:reset:@2", seed=0) as px:
        fut = _client(w.port, px).submit([1], max_new_tokens=4)
        with pytest.raises(ConnectionError) as ei:
            fut.result(timeout=5.0)
        assert not isinstance(ei.value, ServingError)
    assert px.fire_counts().get("down") == 1


def test_trunc_mid_frame_is_untyped_connection_error(wire):
    w = wire(HAPPY)
    with NetChaosProxy(w.port, specs="down:trunc:@2", seed=0) as px:
        fut = _client(w.port, px).submit([1], max_new_tokens=4)
        with pytest.raises(ConnectionError) as ei:
            fut.result(timeout=5.0)
        assert not isinstance(ei.value, ServingError)


def test_same_seed_same_frames_same_fires(wire):
    """The determinism contract: fixed seed + fixed frame sequence ⇒
    identical injection decisions, run to run."""
    counts = []
    for _ in range(2):
        w = wire(HAPPY)
        with NetChaosProxy(w.port, specs="down:delay:0.5:1", seed=11) as px:
            fut = _client(w.port, px).submit([1], max_new_tokens=4)
            np.testing.assert_array_equal(fut.result(timeout=5.0), OUT)
            counts.append((px.hit_counts(), px.fire_counts()))
    assert counts[0] == counts[1]


def test_env_var_arms_the_client_automatically(wire, monkeypatch):
    monkeypatch.setenv("PADDLE_NETCHAOS", "down:delay:1.0:5")
    monkeypatch.setenv("PADDLE_NETCHAOS_SEED", "2")
    w = wire(HAPPY)
    cli = _client(w.port)                 # no proxy injected by hand
    fut = cli.submit([1], max_new_tokens=4)
    np.testing.assert_array_equal(fut.result(timeout=5.0), OUT)
    assert cli._nc_proxy not in (None, False)
    assert cli._nc_proxy.fire_counts().get("down", 0) >= 3
    cli.stop()                            # stop() owns the proxy too
    assert cli._nc_proxy is None


def test_netchaos_off_means_no_proxy_object(wire, monkeypatch):
    monkeypatch.delenv("PADDLE_NETCHAOS", raising=False)
    w = wire(HAPPY)
    cli = _client(w.port)
    fut = cli.submit([1], max_new_tokens=4)
    np.testing.assert_array_equal(fut.result(timeout=5.0), OUT)
    assert cli._nc_proxy is False         # one getenv, then cached off


# -- config cross-check satellite --------------------------------------------

def test_timeout_misconfig_warns_on_stderr_and_metric(capsys):
    import paddlepaddle_tpu.observability as obs

    obs.reset()
    try:
        RemoteReplicaClient(address=1, name="bad",
                            heartbeat_timeout_s=0.4)   # <= server 0.5 s
        err = capsys.readouterr().err
        assert "heartbeat interval" in err and "stall watchdog" in err
        text = obs.to_prometheus_text()
        assert "paddle_replica_timeout_misconfig_total" in text
    finally:
        obs.reset()


def test_sane_timeouts_do_not_warn(capsys):
    RemoteReplicaClient(address=1, name="ok", heartbeat_timeout_s=2.0)
    assert "stall watchdog" not in capsys.readouterr().err


# -- alert-rules satellite ----------------------------------------------------

def test_replica_stalled_alert_rules_are_registered():
    from paddlepaddle_tpu.observability.alerts import default_rules

    rules = {r.name: r for r in default_rules()}
    warn = rules["replica_stalled"]
    page = rules["replica_stalled_sustained"]
    assert warn.severity == "warn" and page.severity == "page"
    assert all(c.series == "paddle_replica_stalls_total"
               for c in warn.conditions + page.conditions)
    # the page needs BOTH a fast and a slow window — a single trip must
    # never page
    assert len(page.conditions) == 2
    assert {c.window_s for c in page.conditions} == {60.0, 300.0}


# -- the real-process drill (chaos tier, via tools/run_chaos.sh) --------------

@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_process_fleet_survives_hostile_network():
    """The hostile-network drill over REAL OS processes: a 2-process
    fleet behind the router, the wire to r0 broken by the netchaos proxy.

    * blackhole mid-stream → the stall watchdog trips within
      ~heartbeat_timeout_s, the router fails over, the future completes
      with the SAME tokens — zero lost futures;
    * idempotent resubmit: the same req_uid against a real replica
      replays the cached terminal (dedup counter on the replica's own
      metrics endpoint) token-exact;
    * corrupt frame under CRC → WireCorruptionError (typed retryable),
      retried to completion — never silently-wrong tokens.
    """
    import paddlepaddle_tpu.observability as obs
    from paddlepaddle_tpu.inference.remote_replica import (
        ProcessReplicaFactory,
        _parse_reply,
        _recv_frame,
        _send_frame,
    )
    from paddlepaddle_tpu.inference.router import ServingRouter

    obs.reset()
    factory = ProcessReplicaFactory(
        preset="tiny", warmup="off",
        supervisor_kw={"ready_timeout_s": 180.0},
        client_kw={"heartbeat_timeout_s": 1.0})
    clients = [factory(name=f"nc{i}") for i in range(2)]
    router = ServingRouter(clients, probe_interval_s=60.0)
    router.start()
    prompt = np.arange(6, dtype=np.int32)
    proxies = []

    def _arm(idx, spec):
        for px_old in proxies:
            px_old.stop()
        px = NetChaosProxy(clients[idx].address, specs=spec,
                           seed=1234, name=f"drill:{spec}").start()
        proxies.append(px)
        clients[idx]._nc_proxy = px
        return px

    def _force(idx):
        router._probe_once()
        for i, rep in enumerate(router._replicas):
            rep.snapshot = dict(rep.snapshot or {}, ok=True,
                                est_wait_s=(0.0 if i == idx else 30.0))

    try:
        # prime decode programs on BOTH replicas, and grab the control
        # tokens every chaotic submit must still produce
        control = clients[0].submit(prompt, max_new_tokens=4).result(180)
        np.testing.assert_array_equal(
            clients[1].submit(prompt, max_new_tokens=4).result(180),
            control)

        # 1) blackhole mid-stream: frame 2 of r0's submit stream (the one
        #    right after accepted) vanishes and the wire goes silent
        _arm(0, "down:blackhole:@2")
        _force(0)
        t0 = time.perf_counter()
        out = router.submit(prompt, max_new_tokens=4).result(60)
        took = time.perf_counter() - t0
        np.testing.assert_array_equal(out, control)
        assert took < 20.0, f"failover took {took:.1f}s"
        assert router.stats["retries"] + router.stats["failovers"] >= 1
        text = obs.to_prometheus_text()
        assert "paddle_replica_stalls_total" in text
        assert "paddle_netchaos_injections_total" in text

        # 2) idempotent resubmit against the real replica process
        clients[0]._nc_proxy = False          # direct wire for this leg
        uid = "drill-dedup-uid"
        first = clients[1].submit(prompt, max_new_tokens=4,
                                  req_uid=uid).result(60)
        again = clients[1].submit(prompt, max_new_tokens=4,
                                  req_uid=uid).result(60)
        np.testing.assert_array_equal(first, control)
        np.testing.assert_array_equal(again, control)
        s = clients[1]._connect()
        try:                                  # the replica's OWN registry
            _send_frame(s, struct.pack("<IB", 0x50444331, 4))
            status, c = _parse_reply(_recv_frame(s))
        finally:
            s.close()
        assert status == 0
        (n,) = struct.unpack_from("<I", c.b, c.o)
        scrape = c.b[c.o + 4:c.o + 4 + n].decode()
        assert "paddle_capi_dedup_replays_total" in scrape

        # 3) corrupt under CRC: typed WireCorruptionError, retried clean
        _arm(0, "down:corrupt:@2")
        _force(0)
        out = router.submit(prompt, max_new_tokens=4).result(60)
        np.testing.assert_array_equal(out, control)
        assert "paddle_wire_corruption_total" in obs.to_prometheus_text()
    finally:
        router.stop()
        for px in proxies:
            px.stop()
        for cl in clients:
            try:
                cl.supervisor.stop(drain_timeout=2.0)
            except Exception:
                pass
        obs.reset()
