"""hapi Model.fit, vision zoo/transforms/datasets, distribution package."""

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; fast tier covers this module via test_fast_smokes.py

import numpy as np
import pytest
from scipy import stats as sps

import paddlepaddle_tpu as paddle


def test_model_fit_evaluate_predict():
    from paddlepaddle_tpu.vision.datasets import FakeData
    from paddlepaddle_tpu.vision.models import LeNet

    train = FakeData(num_samples=32, image_shape=(1, 28, 28), num_classes=10)
    model = paddle.Model(LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    hist = model.fit(train, epochs=1, batch_size=8, verbose=0)
    assert len(hist) == 1 and "loss" in hist[0]
    logs = model.evaluate(train, batch_size=8, verbose=0)
    assert "eval_loss" in logs and "eval_acc" in logs
    preds = model.predict(train, batch_size=8, stack_outputs=True)
    assert preds[0].shape == (32, 10)


def test_model_save_load(tmp_path):
    from paddlepaddle_tpu.vision.models import LeNet

    m = paddle.Model(LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
    m.prepare(opt, paddle.nn.CrossEntropyLoss())
    p = str(tmp_path / "ckpt")
    m.save(p)
    m2 = paddle.Model(LeNet())
    m2.prepare(paddle.optimizer.Adam(learning_rate=1e-3, parameters=m2.parameters()),
               paddle.nn.CrossEntropyLoss())
    m2.load(p)
    w1 = m.network.features[0].weight.numpy()
    w2 = m2.network.features[0].weight.numpy()
    np.testing.assert_allclose(w1, w2)


def test_summary():
    from paddlepaddle_tpu.vision.models import LeNet

    info = paddle.summary(LeNet(), (1, 1, 28, 28))
    assert info["total_params"] > 0
    assert info["trainable_params"] <= info["total_params"]


def test_vision_models_forward():
    from paddlepaddle_tpu.vision.models import mobilenet_v2, vgg11, alexnet

    x = np.random.default_rng(0).standard_normal((1, 3, 64, 64)).astype(np.float32)
    for net in (vgg11(num_classes=7), mobilenet_v2(num_classes=7)):
        out = net(x)
        assert out.shape == [1, 7]
    xa = np.random.default_rng(0).standard_normal((1, 3, 224, 224)).astype(np.float32)
    assert alexnet(num_classes=5)(xa).shape == [1, 5]


def test_transforms():
    from paddlepaddle_tpu.vision import transforms as T

    img = (np.random.default_rng(0).random((32, 32, 3)) * 255).astype(np.uint8)
    pipe = T.Compose([T.Resize(28), T.RandomHorizontalFlip(1.0), T.ToTensor(),
                      T.Normalize(mean=[0.5] * 3, std=[0.5] * 3)])
    out = pipe(img)
    assert out.shape == (3, 28, 28)
    assert out.dtype == np.float32


def test_distribution_normal():
    from paddlepaddle_tpu.distribution import Normal, kl_divergence

    paddle.seed(0)
    d = Normal(0.0, 1.0)
    s = d.sample([2000])
    assert abs(float(s.numpy().mean())) < 0.1
    lp = d.log_prob(paddle.to_tensor(np.array([0.5], np.float32)))
    np.testing.assert_allclose(lp.numpy(), sps.norm.logpdf(0.5), rtol=1e-5)
    kl = kl_divergence(Normal(0.0, 1.0), Normal(1.0, 2.0))
    ref = sps.norm.entropy(0, 1)  # sanity: kl positive and finite
    assert float(np.asarray(kl.numpy())) > 0


def test_distribution_log_probs_match_scipy():
    from paddlepaddle_tpu import distribution as D

    checks = [
        (D.Exponential(2.0), sps.expon(scale=0.5), 0.7),
        (D.Laplace(0.0, 2.0), sps.laplace(0, 2), 0.3),
        (D.Gamma(2.0, 3.0), sps.gamma(2.0, scale=1 / 3.0), 0.9),
        (D.Beta(2.0, 3.0), sps.beta(2, 3), 0.4),
        (D.Poisson(3.0), sps.poisson(3.0), 2.0),
        (D.Gumbel(0.0, 1.0), sps.gumbel_r(0, 1), 0.2),
    ]
    for dist, ref, x in checks:
        lp = float(np.asarray(dist.log_prob(paddle.to_tensor(np.array(x, np.float32))).numpy()))
        ref_lp = ref.logpmf(x) if hasattr(ref, "logpmf") else ref.logpdf(x)
        np.testing.assert_allclose(lp, ref_lp, rtol=1e-4), type(dist)


def test_distribution_categorical_and_bernoulli():
    from paddlepaddle_tpu.distribution import Bernoulli, Categorical, kl_divergence

    c = Categorical(logits=np.log(np.array([0.2, 0.3, 0.5], np.float32)))
    lp = c.log_prob(paddle.to_tensor(np.array([2], np.int64)))
    np.testing.assert_allclose(np.asarray(lp.numpy()), [np.log(0.5)], rtol=1e-5)
    ent = float(np.asarray(c.entropy().numpy()))
    np.testing.assert_allclose(ent, sps.entropy([0.2, 0.3, 0.5]), rtol=1e-5)
    b = Bernoulli(0.3)
    kl = kl_divergence(b, Bernoulli(0.5))
    assert float(np.asarray(kl.numpy())) > 0


def test_distribution_grad_through_log_prob():
    from paddlepaddle_tpu.distribution import Normal

    loc = paddle.to_tensor(np.array(0.5, np.float32), stop_gradient=False)
    d = Normal(loc, 1.0)
    lp = d.log_prob(paddle.to_tensor(np.array(1.0, np.float32)))
    lp.backward()
    # d/dloc logpdf = (x - loc) / var = 0.5
    np.testing.assert_allclose(float(loc.grad.numpy()), 0.5, rtol=1e-5)
