"""MoE routing, expert-parallel sharding, and MoE LM training tests."""

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; fast tier covers this module via test_fast_smokes.py

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.models.moe import MoEConfig, MoEForCausalLM
from paddlepaddle_tpu.parallel.moe import MoELayer, SwitchGate, moe_sharding_rules


def test_moe_layer_forward_shapes_and_aux():
    m = MoELayer(d_model=16, d_hidden=32, num_experts=4)
    x = np.random.default_rng(0).standard_normal((2, 8, 16)).astype(np.float32)
    y = m(x)
    assert y.shape == [2, 8, 16]
    assert m.l_aux is not None and np.isfinite(float(m.l_aux.numpy()))


def test_moe_single_expert_matches_dense_ffn():
    """E=1 top-1 with ample capacity == ordinary swiglu FFN on same weights."""
    import jax.numpy as jnp

    m = MoELayer(d_model=8, d_hidden=16, num_experts=1,
                 gate=SwitchGate(8, 1), capacity_factor=8.0)
    x = np.random.default_rng(0).standard_normal((1, 4, 8)).astype(np.float32)
    y = m(x)
    wg = np.asarray(m.w_gate_proj.numpy())[0]
    wu = np.asarray(m.w_up_proj.numpy())[0]
    wd = np.asarray(m.w_down_proj.numpy())[0]
    xf = x.reshape(4, 8)
    silu = lambda a: a / (1 + np.exp(-a))
    ref = (silu(xf @ wg) * (xf @ wu)) @ wd
    np.testing.assert_allclose(y.numpy().reshape(4, 8), ref, rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
    m = MoELayer(d_model=8, d_hidden=8, num_experts=2,
                 gate=SwitchGate(8, 2), capacity_factor=0.1)
    x = np.random.default_rng(0).standard_normal((1, 64, 8)).astype(np.float32)
    y = m(x)  # most tokens dropped -> zeros, but finite
    assert np.isfinite(y.numpy()).all()


def test_moe_lm_train_decreases():
    from paddlepaddle_tpu.jit.train import TrainStep
    from paddlepaddle_tpu.optimizer import AdamW

    m = MoEForCausalLM(MoEConfig.tiny())
    opt = AdamW(learning_rate=5e-3, parameters=m.parameters())
    step = TrainStep(m, opt, lambda mm, ids, labels: mm(ids, labels=labels))
    ids = np.random.default_rng(0).integers(0, 128, (4, 16)).astype(np.int32)
    losses = [float(step(ids, ids).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("mode", ["einsum", "sorted"])
def test_moe_expert_parallel_sharded(mode):
    """einsum is the documented ep-mesh lowering (keep it covered under
    ShardedTrainStep even though the single-chip default is 'sorted')."""
    import dataclasses

    import jax

    from paddlepaddle_tpu.distributed.mesh import ProcessMesh
    from paddlepaddle_tpu.optimizer import AdamW
    from paddlepaddle_tpu.parallel import ShardedTrainStep

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = ProcessMesh(shape=[2, 4], dim_names=["dp", "ep"])
    m = MoEForCausalLM(dataclasses.replace(MoEConfig.tiny(), dispatch_mode=mode))
    opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
    step = ShardedTrainStep(m, opt, lambda mm, ids, labels: mm(ids, labels=labels),
                            mesh=mesh, rules=moe_sharding_rules(),
                            data_axes=("dp",))
    ids = np.random.default_rng(0).integers(0, 128, (4, 16)).astype(np.int32)
    losses = [float(step(ids, ids).numpy()) for _ in range(5)]
    assert losses[-1] < losses[0]
    name = next(n for n in step.params if n.endswith("w_gate_proj"))
    assert not step.params[name].sharding.is_fully_replicated


@pytest.mark.parametrize("fast_mode", ["sorted", "dropless"])
def test_fast_dispatch_matches_einsum(fast_mode):
    """The fast paths (counting-sort capacity einsum / dropless ragged_dot —
    fused_moe.py analogues) are numerically identical to the GShard einsum
    path when capacity is ample, for both top-2 (renormalized gates) and
    top-1 (raw Switch probability)."""
    from paddlepaddle_tpu.parallel.moe import GShardGate

    x = np.random.default_rng(0).standard_normal((2, 8, 16)).astype(np.float32)
    for gate_cls, name in ((GShardGate, "top2"), (SwitchGate, "top1")):
        paddle.seed(3)
        m_s = MoELayer(16, 32, 4, gate=gate_cls(16, 4), capacity_factor=8.0,
                       dispatch_mode=fast_mode)
        paddle.seed(3)
        m_e = MoELayer(16, 32, 4, gate=gate_cls(16, 4), capacity_factor=8.0,
                       dispatch_mode="einsum")
        for (_, p1), (_, p2) in zip(sorted(m_s.raw_state().items()),
                                    sorted(m_e.raw_state().items())):
            p2._replace_data(p1._data)
        ys, ye = m_s(x), m_e(x)
        np.testing.assert_allclose(ys.numpy(), ye.numpy(), atol=1e-5,
                                   err_msg=name)
        # aux-loss normalization matches across modes too
        np.testing.assert_allclose(float(m_s.l_aux.numpy()),
                                   float(m_e.l_aux.numpy()), rtol=0.5)


def test_sorted_capacity_drop_priority_matches_einsum():
    """Under capacity PRESSURE the sorted path must drop the same entries
    as the einsum path: first choices fill capacity before any second
    choice (the shared fill counter in _topk_routing) — round-major entry
    order in the counting sort reproduces it."""
    from paddlepaddle_tpu.parallel.moe import GShardGate

    x = np.random.default_rng(1).standard_normal((1, 32, 16)).astype(np.float32)
    paddle.seed(5)
    m_s = MoELayer(16, 32, 4, gate=GShardGate(16, 4), capacity_factor=0.6,
                   dispatch_mode="sorted")
    paddle.seed(5)
    m_e = MoELayer(16, 32, 4, gate=GShardGate(16, 4), capacity_factor=0.6,
                   dispatch_mode="einsum")
    for (_, p1), (_, p2) in zip(sorted(m_s.raw_state().items()),
                                sorted(m_e.raw_state().items())):
        p2._replace_data(p1._data)
    np.testing.assert_allclose(m_s(x).numpy(), m_e(x).numpy(), atol=1e-5)


@pytest.mark.parametrize("fast_mode", ["sorted", "dropless"])
def test_fast_dispatch_blocked_prefix_sum_branch(fast_mode):
    """N = T*k >= 512 exercises _counting_sort's blockwise tril-MATMUL
    prefix-sum branch (bf16 one-hots + cross-block offset stitching), which
    small parity tests never reach."""
    from paddlepaddle_tpu.parallel.moe import GShardGate

    x = np.random.default_rng(2).standard_normal((2, 128, 16)).astype(np.float32)
    paddle.seed(7)
    m_s = MoELayer(16, 32, 4, gate=GShardGate(16, 4), capacity_factor=8.0,
                   dispatch_mode=fast_mode)
    paddle.seed(7)
    m_e = MoELayer(16, 32, 4, gate=GShardGate(16, 4), capacity_factor=8.0,
                   dispatch_mode="einsum")
    for (_, p1), (_, p2) in zip(sorted(m_s.raw_state().items()),
                                sorted(m_e.raw_state().items())):
        p2._replace_data(p1._data)
    np.testing.assert_allclose(m_s(x).numpy(), m_e(x).numpy(), atol=1e-4)


@pytest.mark.parametrize("fast_mode", ["sorted", "dropless"])
def test_fast_dispatch_gradients_match_einsum(fast_mode):
    """The hand-written gather-only custom vjps (_dispatch_gather /
    _combine_gather / _slot_*) must produce the same expert-weight and
    input gradients as autodiff through the einsum path; and the router
    gradient must flow through the gate weight (the top-1 case uses the
    raw probability, not a renormalized ~1.0)."""
    from paddlepaddle_tpu.parallel.moe import GShardGate

    x = np.random.default_rng(3).standard_normal((2, 16, 16)).astype(np.float32)
    grads = {}
    for mode in (fast_mode, "einsum"):
        paddle.seed(9)
        m = MoELayer(16, 32, 4, gate=GShardGate(16, 4), capacity_factor=8.0,
                     dispatch_mode=mode)
        xt = paddle.to_tensor(x, stop_gradient=False)
        (m(xt) ** 2).sum().backward()
        grads[mode] = {
            "x": xt.grad.numpy(),
            "w_gate": m.w_gate_proj.grad.numpy(),
            "w_down": m.w_down_proj.grad.numpy(),
            "gate": m.gate.weight.grad.numpy(),
        }
    for name in grads[fast_mode]:
        np.testing.assert_allclose(grads[fast_mode][name], grads["einsum"][name],
                                   rtol=1e-3, atol=1e-4, err_msg=name)

    m = MoELayer(16, 32, 4, gate=SwitchGate(16, 4), capacity_factor=8.0,
                 dispatch_mode=fast_mode)
    xt = paddle.to_tensor(x, stop_gradient=False)
    m(xt).sum().backward()
    g = m.gate.weight.grad
    assert g is not None and np.abs(g.numpy()).sum() > 1e-6


def test_sorted_dispatch_honors_custom_gate_by_fallback():
    """A gate overriding routing() keeps its behavior (einsum fallback)."""
    from paddlepaddle_tpu.parallel.moe import NaiveGate

    calls = []

    class MyGate(NaiveGate):
        def routing(self, x_flat, capacity):
            calls.append(1)
            return super().routing(x_flat, capacity)

    m = MoELayer(16, 32, 4, gate=MyGate(16, 4), dispatch_mode="sorted")
    m(np.random.default_rng(0).standard_normal((1, 4, 16)).astype(np.float32))
    assert calls  # custom routing ran

    with pytest.raises(ValueError, match="dispatch_mode"):
        MoELayer(16, 32, 4, dispatch_mode="Sorted")


def test_dropless_alignment_parity():
    """128-aligned padded-group dropless (MXU tile-boundary knob) must match
    the unpadded path exactly in value and all gradients."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddlepaddle_tpu.parallel.moe import _dropless_moe_ffn

    T, d, h, E, k = 256, 16, 24, 4, 2
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((T, d)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)
    wg = jnp.asarray(rng.standard_normal((E, d, h)) * 0.1, jnp.float32)
    wu = jnp.asarray(rng.standard_normal((E, d, h)) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.standard_normal((E, h, d)) * 0.1, jnp.float32)

    def loss(align):
        def f(x, wg, wu, wd):
            y, aux = _dropless_moe_ffn(x, logits, wg, wu, wd, k, align=align)
            return (y * y).mean() + aux
        return f

    l1, g1 = jax.value_and_grad(loss(1), argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    l128, g128 = jax.value_and_grad(loss(128), argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    np.testing.assert_allclose(float(l1), float(l128), rtol=1e-6)
    for a, b in zip(g1, g128):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
