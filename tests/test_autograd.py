"""Eager autograd tape tests (reference model: eager backward tests +
numeric grad checks from OpTest)."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from op_test import check_grad


def test_simple_backward():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain_backward():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x * x  # x^3 -> 3x^2 = 12
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 12.0, rtol=1e-6)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5, 5])
    x.clear_grad()
    assert x.grad is None


def test_broadcast_grad():
    x = paddle.to_tensor(np.ones((3, 4), np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    ((x + b) * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((3, 4), 2.0))
    np.testing.assert_allclose(b.grad.numpy(), np.full((4,), 6.0))


def test_matmul_grad_numeric():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4, 2).astype(np.float32)
    check_grad(paddle.matmul, [a, b])


def test_mixed_ops_grad_numeric():
    x = np.random.uniform(0.5, 1.5, (3, 3)).astype(np.float32)

    def fn(t):
        return (paddle.exp(t) * paddle.sqrt(t) + paddle.sin(t)).sum()

    check_grad(fn, [x])


def test_stop_gradient():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    (x * y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    z = y.detach()
    assert z.stop_gradient
    w = z * 3
    # no grad path: reference silently skips (backward.cc "Skip auto grad...")
    w.backward()
    assert w.grad is None and x.grad is None


def test_no_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y._grad_node is None


def test_non_scalar_backward_implicit_ones():
    # Paddle fills an implicit all-ones grad for any shape
    # (tensor_patch_methods.py:270) — no scalar-only restriction.
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])
    x.clear_grad()
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_double_backward_without_retain_raises():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_paddle_grad():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x).sum()
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [2, 4])
    assert x.grad is None  # grad() does not accumulate into leaves


def test_grad_allow_unused():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    z = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).sum()
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x, z])
    gx, gz = paddle.grad(y, [x, z], allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_multi_output_op_grad():
    x = np.random.randn(4, 6).astype(np.float32)
    t = paddle.to_tensor(x, stop_gradient=False)
    parts = paddle.split(t, 2, axis=1)
    loss = (parts[0] * 2).sum() + (parts[1] * 3).sum()
    loss.backward()
    ref = np.concatenate([np.full((4, 3), 2.0), np.full((4, 3), 3.0)], axis=1)
    np.testing.assert_allclose(t.grad.numpy(), ref)


def test_getitem_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3), stop_gradient=False)
    y = x[0].sum() * 2
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[2, 2, 2], [0, 0, 0]])


def test_register_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []
    h = x.register_hook(lambda g: seen.append(g.numpy()) or (g * 2))
    (x * 3).sum().backward()
    assert seen and seen[0][0] == pytest.approx(3.0)
    np.testing.assert_allclose(x.grad.numpy(), [6.0])
    h.remove()


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [2, 4])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_pylayer_none_grad_does_not_block():
    # A PyLayer backward returning None must still unblock the producer so
    # gradient arriving via other consumers is not dropped.
    class NoGrad(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 5

        @staticmethod
        def backward(ctx, grad):
            return None

    a = paddle.to_tensor([1.0], stop_gradient=False)
    z = a * 2
    w = NoGrad.apply(z) + z
    w.sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), [2.0])


def test_nonleaf_hook_fires():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    h = x * 3  # non-leaf
    seen = []
    h.register_hook(lambda g: seen.append(g.numpy()) or (g * 10))
    (h * 2).sum().backward()
    assert seen and seen[0][0] == pytest.approx(2.0)
    np.testing.assert_allclose(x.grad.numpy(), [60.0])  # 2 * 10 * 3


def test_hook_remove_then_add():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    calls = []
    h1 = x.register_hook(lambda g: calls.append("a"))
    x.register_hook(lambda g: calls.append("b"))
    h1.remove()
    x.register_hook(lambda g: calls.append("c"))
    (x * 2).sum().backward()
    assert calls == ["b", "c"]


def test_no_grad_vars():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    w = x * 3
    y = (w * x).sum()  # y = 3x^2; through-w path contributes 3x, direct path 3x
    (gx,) = paddle.grad(y, [x], no_grad_vars=[w])
    # gradient through w severed: only the direct x edge remains -> w = 6
    np.testing.assert_allclose(gx.numpy(), [6.0])


def test_functional_jacobian():
    x = np.array([1.0, 2.0], np.float32)
    jac = paddle.autograd.functional_jacobian(lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(np.asarray(jac.numpy() if hasattr(jac, 'numpy') else jac), [2, 4], rtol=1e-5)


def test_grad_hook_fires_once_with_accumulated_grad():
    """Hooks see the FULL accumulated gradient, once per tensor per backward
    (reference per-tensor hook semantics, paddle/fluid/eager/hooks.h)."""
    import paddlepaddle_tpu as paddle

    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    calls = []

    def hook(g):
        calls.append(float(g.numpy()[0]))
        return g.clip(-1, 1)

    x.register_hook(hook)
    y = x * 2 + x * 3
    y.backward()
    assert calls == [5.0]
    assert float(x.grad.numpy()[0]) == 1.0


def test_interior_hook_affects_upstream():
    import paddlepaddle_tpu as paddle

    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    h = x * 2
    h.register_hook(lambda g: g * 10)
    z = h * 4 + h
    z.backward()
    assert float(x.grad.numpy()[0]) == 100.0


def test_inplace_ops_autograd_semantics():
    """Reference dygraph semantics for in-place ops (round-4 fix): mutating
    a LEAF that requires grad raises; an intermediate keeps exact grads
    through (and across chains of) in-place mutations — previously the
    rebind created a tape self-loop and .grad silently stayed None."""
    import numpy as np
    import pytest

    import paddlepaddle_tpu as paddle

    x = paddle.to_tensor(np.ones((3,), np.float32))
    x.stop_gradient = False
    with pytest.raises(RuntimeError, match="leaf"):
        paddle.add_(x, x)

    y = x * 2
    paddle.add_(y, x)                    # y = 3x
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 3.0)

    x2 = paddle.to_tensor(np.full((2,), 2.0, np.float32))
    x2.stop_gradient = False
    z = x2 * 1.0
    paddle.multiply_(z, x2)              # z = x^2
    paddle.add_(z, x2)                   # z = x^2 + x
    z.sum().backward()
    np.testing.assert_allclose(x2.grad.numpy(), 2 * 2.0 + 1)

    # no_grad leaf mutation stays allowed (raw value update)
    w = paddle.to_tensor(np.zeros((2,), np.float32))
    w.stop_gradient = False
    with paddle.no_grad():
        paddle.add_(w, paddle.to_tensor(np.ones((2,), np.float32)))
    np.testing.assert_allclose(w.numpy(), 1.0)
