"""Flagship Llama model tests: forward shape, loss decrease, sharded step."""

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; fast tier covers this module via test_fast_smokes.py

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM, llama_sharding_rules


def _tiny():
    return LlamaForCausalLM(LlamaConfig.tiny(vocab_size=64, hidden_size=32,
                                             layers=2, heads=4, kv_heads=2, max_len=32))


def test_forward_shapes():
    m = _tiny()
    ids = np.random.default_rng(0).integers(0, 64, (2, 16)).astype(np.int32)
    logits = m(ids)
    assert logits.shape == [2, 16, 64]


def test_loss_finite_and_backward():
    m = _tiny()
    ids = np.random.default_rng(0).integers(0, 64, (2, 16)).astype(np.int32)
    loss = m(ids, labels=ids)
    assert np.isfinite(float(loss.numpy()))
    loss.backward()
    g = m.model.layers[0].self_attn.q_proj.weight.grad
    assert g is not None and float(np.abs(g.numpy()).sum()) > 0


def test_train_step_loss_decreases():
    from paddlepaddle_tpu.jit.train import TrainStep
    from paddlepaddle_tpu.optimizer import AdamW

    m = _tiny()
    opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
    step = TrainStep(m, opt, lambda mm, ids, labels: mm(ids, labels=labels))
    ids = np.random.default_rng(0).integers(0, 64, (4, 16)).astype(np.int32)
    losses = [float(step(ids, ids).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_sharded_train_step():
    import jax

    from paddlepaddle_tpu.distributed.mesh import ProcessMesh
    from paddlepaddle_tpu.optimizer import AdamW
    from paddlepaddle_tpu.parallel import ShardedTrainStep

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = ProcessMesh(shape=[2, 2, 2], dim_names=["dp", "fsdp", "tp"])
    m = _tiny()
    opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
    step = ShardedTrainStep(m, opt, lambda mm, ids, labels: mm(ids, labels=labels),
                            mesh=mesh, rules=llama_sharding_rules())
    ids = np.random.default_rng(0).integers(0, 64, (8, 16)).astype(np.int32)
    losses = [float(step(ids, ids).numpy()) for _ in range(6)]
    assert losses[-1] < losses[0]
    # params actually sharded: q_proj weight lives on tp×fsdp
    name = next(n for n in step.params if n.endswith("q_proj.weight"))
    assert not step.params[name].sharding.is_fully_replicated


def test_gqa_matches_mha_repeat():
    """GQA with kv repeated == MHA when kv weights are tiled."""
    cfg = LlamaConfig.tiny(vocab_size=32, hidden_size=32, layers=1, heads=4, kv_heads=4, max_len=16)
    m = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(1).integers(0, 32, (1, 8)).astype(np.int32)
    out = m(ids)
    assert np.isfinite(out.numpy()).all()


def test_remat_policy_dots_grad_parity():
    """recompute with the "dots" checkpoint policy (save matmul outputs,
    r5) must produce the same loss AND grads as no recompute at all."""
    import jax
    import numpy as np

    from paddlepaddle_tpu.core import autograd as ag
    from paddlepaddle_tpu.core.dispatch import unwrap
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    ids = np.random.default_rng(0).integers(0, 128, (2, 16)).astype(np.int32)
    results = {}
    state0 = None
    for tag, kw in (("plain", {}),
                    ("dots", dict(recompute=True, remat_policy="dots"))):
        paddle.seed(0)
        cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=32, layers=2,
                               heads=4, kv_heads=2, max_len=32)
        for k, v in kw.items():
            setattr(cfg, k, v)
        model = LlamaForCausalLM(cfg)
        if state0 is None:
            state0 = {k: np.asarray(v) for k, v in
                      model.functional_state(trainable_only=True).items()}
        buffers = {k: v for k, v in model.functional_state().items()
                   if k not in state0}

        def loss_of(p):
            with ag.no_grad():
                full = dict(p)
                full.update(buffers)
                with model.bind_state(full):
                    return unwrap(model(paddle.to_tensor(ids),
                                        labels=paddle.to_tensor(ids)))

        loss, grads = jax.jit(jax.value_and_grad(loss_of))(state0)
        results[tag] = (float(np.asarray(loss)),
                        {k: np.asarray(v) for k, v in grads.items()})
    l_plain, g_plain = results["plain"]
    l_dots, g_dots = results["dots"]
    assert abs(l_plain - l_dots) < 1e-4, (l_plain, l_dots)
    for k in g_plain:
        np.testing.assert_allclose(g_dots[k], g_plain[k], rtol=2e-3,
                                   atol=1e-5, err_msg=k)
