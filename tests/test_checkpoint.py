"""Distributed checkpoint: roundtrip, async save, reshard-on-load."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.distributed import checkpoint as dist_ckpt


def test_save_load_roundtrip(tmp_path):
    m = paddle.nn.Linear(4, 3)
    sd = m.state_dict()
    orig = {k: v.numpy().copy() for k, v in sd.items()}
    dist_ckpt.save_state_dict(sd, str(tmp_path / "ckpt"))

    m2 = paddle.nn.Linear(4, 3)
    sd2 = m2.state_dict()
    dist_ckpt.load_state_dict(sd2, str(tmp_path / "ckpt"))
    for k in orig:
        np.testing.assert_allclose(sd2[k].numpy(), orig[k])


def test_async_save(tmp_path):
    m = paddle.nn.Linear(8, 8)
    sd = m.state_dict()
    dist_ckpt.save_state_dict(sd, str(tmp_path / "ckpt"), async_save=True)
    dist_ckpt.wait_all_saves()
    meta = dist_ckpt.get_checkpoint_metadata(str(tmp_path / "ckpt"))
    assert set(meta["tensors"]) == set(sd.keys())


def test_reshard_on_load_across_meshes(tmp_path):
    """Save params sharded on mesh A; load into params sharded on mesh B."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlepaddle_tpu.distributed.mesh import ProcessMesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    mesh_a = ProcessMesh(shape=[2, 4], dim_names=["x", "y"]).to_jax()
    mesh_b = ProcessMesh(shape=[4, 2], dim_names=["x", "y"]).to_jax()
    val = np.arange(64, dtype=np.float32).reshape(8, 8)

    t = paddle.to_tensor(val)
    t._replace_data(jax.device_put(t._data, NamedSharding(mesh_a, P("x", "y"))))
    dist_ckpt.save_state_dict({"w": t}, str(tmp_path / "ckpt"))
    meta = dist_ckpt.get_checkpoint_metadata(str(tmp_path / "ckpt"))
    assert meta["tensors"]["w"]["sharding"]["mesh_shape"] == [2, 4]

    t2 = paddle.to_tensor(np.zeros_like(val))
    t2._replace_data(jax.device_put(t2._data, NamedSharding(mesh_b, P("y", "x"))))
    dist_ckpt.load_state_dict({"w": t2}, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(t2.numpy(), val)
    # sharding of the TARGET is preserved (reshard-on-load)
    assert t2._data.sharding.mesh.shape == {"x": 4, "y": 2}


def test_sharded_save_writes_per_shard_files(tmp_path):
    """v2 format: one file per unique shard, none holding the global value,
    replicated shards deduped (reference save_state_dict.py:63,117)."""
    import os

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlepaddle_tpu.distributed.mesh import ProcessMesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    mesh = ProcessMesh(shape=[4, 2], dim_names=["dp", "tp"]).to_jax()
    val = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    t = paddle.to_tensor(val)
    # sharded over tp only -> 2 unique shards, 4-way replicated each
    t._replace_data(jax.device_put(t._data, NamedSharding(mesh, P(None, "tp"))))
    dist_ckpt.save_state_dict({"w": t}, str(tmp_path / "ckpt"))

    meta = dist_ckpt.get_checkpoint_metadata(str(tmp_path / "ckpt"))
    rec = meta["tensors"]["w"]
    assert meta["format"].endswith("v3")
    assert len(rec["shards"]) == 2  # deduped: 8 device shards -> 2 unique
    boxes = sorted(tuple(map(tuple, s["box"])) for s in rec["shards"])
    assert boxes == [((0, 8), (0, 8)), ((0, 8), (8, 16))]
    for s in rec["shards"]:
        shard = np.load(os.path.join(tmp_path / "ckpt", s["file"]))
        assert shard.shape == (8, 8)  # local bytes only, not the global value


def test_reshard_hybrid_to_hybrid(tmp_path):
    """dp4xtp2 -> dp2xfsdp2xtp2 round trip (the VERDICT's target case):
    different axis count, different partition dims, values must survive."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlepaddle_tpu.distributed.mesh import ProcessMesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    rng = np.random.default_rng(7)
    vals = {
        "wq": rng.standard_normal((16, 8)).astype(np.float32),
        "wo": rng.standard_normal((8, 16)).astype(np.float32),
        "scale": rng.standard_normal((16,)).astype(np.float32),
    }
    mesh_a = ProcessMesh(shape=[4, 2], dim_names=["dp", "tp"]).to_jax()
    specs_a = {"wq": P(None, "tp"), "wo": P("tp", None), "scale": P()}
    sd = {}
    for k, v in vals.items():
        t = paddle.to_tensor(v.copy())
        t._replace_data(jax.device_put(t._data, NamedSharding(mesh_a, specs_a[k])))
        sd[k] = t
    dist_ckpt.save_state_dict(sd, str(tmp_path / "ckpt"), async_save=True)
    dist_ckpt.wait_all_saves()

    mesh_b = ProcessMesh(shape=[2, 2, 2], dim_names=["dp", "fsdp", "tp"]).to_jax()
    specs_b = {"wq": P(("dp", "fsdp"), "tp"), "wo": P("tp", "fsdp"),
               "scale": P("fsdp")}
    sd2 = {}
    for k, v in vals.items():
        t = paddle.to_tensor(np.zeros_like(v))
        t._replace_data(jax.device_put(t._data, NamedSharding(mesh_b, specs_b[k])))
        sd2[k] = t
    dist_ckpt.load_state_dict(sd2, str(tmp_path / "ckpt"))
    for k, v in vals.items():
        np.testing.assert_allclose(sd2[k].numpy(), v)
        assert sd2[k]._data.sharding.mesh.shape == {"dp": 2, "fsdp": 2, "tp": 2}


def test_multihost_save_merges_rank_metadata(tmp_path):
    """Two simulated hosts (save_state_dict.py:46,63,145 semantics): each
    writes only its local shards + a rank record; the coordinator merges
    them (deduping boxes both hosts replicate) into one metadata.json that
    loads as the full global state."""
    import numpy as np

    from paddlepaddle_tpu.distributed.checkpoint import LocalShards

    w = np.arange(12, dtype=np.float32).reshape(4, 3)
    b = np.arange(3, dtype=np.float32)
    ck = str(tmp_path / "ckpt")
    # non-coordinator host 1 first: rows 2:4 of w + its replica of b
    dist_ckpt.save_state_dict(
        {"w": LocalShards((4, 3), "float32", [([[2, 4], [0, 3]], w[2:4])]),
         "b": LocalShards((3,), "float32", [([[0, 3]], b)])},
        ck, process_index=1, process_count=2)
    # coordinator host 0: rows 0:2 + its replica of b; merges on return
    dist_ckpt.save_state_dict(
        {"w": LocalShards((4, 3), "float32", [([[0, 2], [0, 3]], w[0:2])]),
         "b": LocalShards((3,), "float32", [([[0, 3]], b)])},
        ck, process_index=0, process_count=2)

    meta = dist_ckpt.get_checkpoint_metadata(ck)
    assert meta["world_size"] == 2
    assert len(meta["tensors"]["w"]["shards"]) == 2
    assert len(meta["tensors"]["b"]["shards"]) == 1  # replica deduped
    out = {"w": np.zeros((4, 3), np.float32), "b": np.zeros((3,), np.float32)}
    dist_ckpt.load_state_dict(out, ck)
    np.testing.assert_allclose(out["w"], w)
    np.testing.assert_allclose(out["b"], b)


def test_multihost_merge_times_out_on_missing_rank(tmp_path):
    from paddlepaddle_tpu.distributed.checkpoint import LocalShards

    with pytest.raises(TimeoutError, match="rank"):
        dist_ckpt.save_state_dict(
            {"w": LocalShards((2,), "float32",
                              [([[0, 2]], np.zeros(2, np.float32))])},
            str(tmp_path / "ckpt"), process_index=0, process_count=2,
            merge_timeout=0.3)


def test_async_save_flushed_at_process_exit(tmp_path):
    """A process that async-saves and exits WITHOUT calling wait_all_saves
    must still leave a complete checkpoint (the atexit flush)."""
    import subprocess
    import sys

    ck = str(tmp_path / "ckpt")
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {repr(str(__import__('pathlib').Path(__file__).resolve().parent.parent))})\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        "import paddlepaddle_tpu as paddle\n"
        "from paddlepaddle_tpu.distributed import checkpoint as dist_ckpt\n"
        "m = paddle.nn.Linear(64, 64)\n"
        f"dist_ckpt.save_state_dict(m.state_dict(), {ck!r}, async_save=True)\n"
        "sys.exit(0)\n"  # no wait_all_saves: atexit must flush
    )
    env = dict(__import__("os").environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    m2 = paddle.nn.Linear(64, 64)
    sd2 = m2.state_dict()
    dist_ckpt.load_state_dict(sd2, ck)  # raises if torn/missing
