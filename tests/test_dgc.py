"""DGCMomentumOptimizer (reference fleet/meta_optimizers/dgc_optimizer.py +
phi/kernels/gpu/dgc_kernel.cu): momentum-before-rampup, top-k error-feedback
compression after, small-tensor exemption, and the fleet strategy wiring."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.optimizer import Adam, DGCMomentumOptimizer, Momentum

rng = np.random.default_rng(11)
BIG = 20000    # >= the reference's 16384 compression floor


def _pair(shape):
    w = rng.standard_normal(shape).astype(np.float32)
    a = paddle.to_tensor(w.copy(), stop_gradient=False)
    b = paddle.to_tensor(w.copy(), stop_gradient=False)
    return a, b


def _step(opt, p, g):
    p._grad = paddle.to_tensor(g)
    opt.step()
    opt.clear_grad()


def test_pre_rampup_matches_momentum():
    a, b = _pair((BIG,))
    dgc = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                               rampup_begin_step=3, parameters=[a])
    mom = Momentum(learning_rate=0.1, momentum=0.9, parameters=[b])
    for _ in range(3):
        g = rng.standard_normal((BIG,)).astype(np.float32)
        _step(dgc, a, g)
        _step(mom, b, g)
    np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-6, atol=1e-7)


def test_small_tensor_never_compressed():
    a, b = _pair((64,))
    dgc = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                               rampup_begin_step=0, parameters=[a])
    mom = Momentum(learning_rate=0.1, momentum=0.9, parameters=[b])
    for _ in range(4):
        g = rng.standard_normal((64,)).astype(np.float32)
        _step(dgc, a, g)
        _step(mom, b, g)
    np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-6, atol=1e-7)


def test_compression_sparsity_and_error_feedback():
    a, _ = _pair((BIG,))
    dgc = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                               rampup_begin_step=0, sparsity=[0.999],
                               parameters=[a])
    p0 = a.numpy().copy()
    g = rng.standard_normal((BIG,)).astype(np.float32)
    _step(dgc, a, g)
    changed = (a.numpy() != p0).sum()
    # k truncates in float arithmetic exactly as the reference kernel does
    # (ratio = 1 - 0.999f -> 0.00099998..., k = int(numel * ratio) = 19)
    k = int(np.float32(BIG) * (np.float32(1.0) - np.float32(0.999)))
    assert max(k, 1) <= changed <= 3 * (k + 1), changed   # ~0.1% touched

    slots = dgc._accumulators[id(a)]
    u, v = np.asarray(slots["u"]), np.asarray(slots["v"])
    np.testing.assert_allclose(u, 0.9 * 0 + g, rtol=1e-6)   # u = m*0 + g
    # error feedback: v holds exactly the unselected residual of (v0 + u)
    sel = a.numpy() != p0
    assert (v[sel] == 0).all()
    np.testing.assert_allclose(v[~sel], g[~sel], rtol=1e-6)
    # the update applied -lr * selected v
    np.testing.assert_allclose(a.numpy()[sel], p0[sel] - 0.1 * g[sel],
                               rtol=1e-5)
    # selected entries are the largest magnitudes
    assert np.abs(g[sel]).min() >= np.abs(g[~sel]).max() - 1e-6


def test_convergence_with_compression():
    target = rng.standard_normal((BIG,)).astype(np.float32)
    a = paddle.to_tensor(np.zeros((BIG,), np.float32), stop_gradient=False)
    dgc = DGCMomentumOptimizer(learning_rate=0.01, momentum=0.9,
                               rampup_begin_step=0, sparsity=[0.9],
                               parameters=[a])
    first = None
    for i in range(60):
        err = a.numpy() - target
        loss = float((err ** 2).mean())
        first = loss if first is None else first
        _step(dgc, a, 2 * err)
    assert loss < 0.25 * first, (first, loss)


def test_grad_clip_contract_and_fleet_wiring():
    from paddlepaddle_tpu.nn import ClipGradByGlobalNorm, ClipGradByNorm

    with pytest.raises(TypeError, match="ClipGradByNorm"):
        DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                             grad_clip=ClipGradByGlobalNorm(1.0),
                             parameters=[paddle.to_tensor([1.0])])
    with pytest.raises(ValueError, match="num_trainers"):
        DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                             grad_clip=ClipGradByNorm(1.0),
                             parameters=[paddle.to_tensor([1.0])])

    from paddlepaddle_tpu.distributed import fleet

    strat = fleet.DistributedStrategy()
    strat.dgc = True
    strat.dgc_configs = {"rampup_begin_step": 2, "rampup_step": 4,
                         "sparsity": [0.99, 0.999]}
    fleet.init(is_collective=True, strategy=strat)
    w = paddle.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    inner = Momentum(learning_rate=0.1, momentum=0.8, parameters=[w])
    wrapped = fleet.distributed_optimizer(inner, strat)
    assert isinstance(wrapped, DGCMomentumOptimizer)
    assert wrapped._momentum == 0.8 and wrapped._rampup_begin == 2.0
    # non-Momentum passes through, as in the reference DGCOptimizer
    adam = Adam(parameters=[w])
    assert fleet.distributed_optimizer(adam, strat) is adam
