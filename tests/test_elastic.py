"""Elastic membership (scale up/down) over the native TCPStore.

Reference surface: python/paddle/distributed/fleet/elastic/manager.py:125,
237-316 — hosts register leases, the manager watches membership and rewrites
the world on scale events; plus the launcher relaunch loop.
"""

import os
import time

import numpy as np
import pytest

from paddlepaddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                        ElasticNode)
from paddlepaddle_tpu.distributed.store import TCPStore


def _store():
    return TCPStore(is_master=True)


def test_scale_up_commits_new_world():
    store = _store()
    mgr = ElasticManager(store, np_range=(1, 4), heartbeat_timeout=1.0)

    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n0.register()
    mgr.scan_once()
    assert mgr.version == 1 and mgr.members == ["hostA"]

    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n1.register()
    mgr.scan_once()
    assert mgr.version == 2 and mgr.members == ["hostA", "hostB"]

    # workers observe the committed world through the store
    version, members = ElasticManager.read_world(store)
    assert version == 2 and members == ["hostA", "hostB"]
    assert n0.world_changed(1) and not n0.world_changed(2)
    n0.stop()
    n1.stop()


def test_scale_down_on_dead_heartbeat():
    store = _store()
    mgr = ElasticManager(store, np_range=(1, 4), heartbeat_timeout=0.4)
    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n0.register()
    n1.register()
    mgr.scan_once()
    assert sorted(mgr.members) == ["hostA", "hostB"]

    n1.stop()  # hostB stops heartbeating
    deadline = time.time() + 5
    while time.time() < deadline and "hostB" in mgr.members:
        time.sleep(0.1)
        mgr.scan_once()
    assert mgr.members == ["hostA"]  # shrunk world committed
    version, members = ElasticManager.read_world(store)
    assert members == ["hostA"] and version >= 2
    n0.stop()


def test_min_np_floor_blocks_undersized_world():
    store = _store()
    mgr = ElasticManager(store, np_range=(2, 4), heartbeat_timeout=0.3)
    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n0.register()
    n1.register()
    mgr.scan_once()
    assert len(mgr.members) == 2

    n1.stop()
    time.sleep(0.8)
    mgr.scan_once()
    # one alive < min_np=2: the old world stays (job blocks rather than
    # committing an undersized membership)
    assert sorted(mgr.members) == ["hostA", "hostB"]
    n0.stop()


def test_wait_for_np_rendezvous():
    store = _store()
    mgr = ElasticManager(store, np_range=(2, 4), heartbeat_timeout=1.0)
    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n0.register()
    with pytest.raises(TimeoutError):
        mgr.wait_for_np(2, timeout=0.5)
    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n1.register()
    version, members = mgr.wait_for_np(2, timeout=5)
    assert version >= 1 and sorted(members) == ["hostA", "hostB"]
    n0.stop()
    n1.stop()


def test_max_np_caps_world():
    store = _store()
    mgr = ElasticManager(store, np_range=(1, 2), heartbeat_timeout=1.0)
    nodes = [ElasticNode(store, f"h{i}", heartbeat_interval=0.1)
             for i in range(3)]
    for n in nodes:
        n.register()
    mgr.scan_once()
    assert len(mgr.members) == 2  # capped at max_np
    # surplus nodes must NOT churn the version on every scan (review
    # finding: identical capped world was re-committed each poll)
    v = mgr.version
    for _ in range(5):
        mgr.scan_once()
    assert mgr.version == v
    for n in nodes:
        n.stop()


# -- r5: the composed kill-resume drill (verdict item 6) ---------------------

_DRILL_WORKER = r"""
import os, sys, time
sys.path.insert(0, os.environ["REPO_DIR"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import paddlepaddle_tpu as paddle
from paddlepaddle_tpu.distributed.host_collectives import get_host_group

rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
ckpt = os.environ["DRILL_CKPT"]
marker = os.environ["DRILL_MARKER"]
out_path = os.environ["DRILL_OUT"]
TOTAL = 10

g = get_host_group() if world > 1 else None

lin = paddle.nn.Linear(4, 1)
opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=lin.parameters())
start = 0
if os.path.exists(ckpt):
    blob = paddle.load(ckpt)
    lin.set_state_dict(blob["model"])
    opt.set_state_dict(blob["opt"])
    start = int(blob["step"])
    if g is not None:
        # deterministic op schedule: one all_reduce PER PARAMETER per
        # finished step, so the group sequence is derivable from the
        # checkpoint (the elastic re-admission contract — a fresh
        # incarnation must rejoin the stream at the exact op index, or its
        # collectives alias a live rank's older slots and read stale data)
        g.rejoin(start * len(lin.parameters()))

# fixed full batch: every rank computes the SAME grads, so the
# allreduce-mean trajectory is world-size independent (solo == duo)
rng = np.random.default_rng(0)
xb = rng.standard_normal((16, 4)).astype(np.float32)
w_true = np.asarray([[1.0], [2.0], [-1.0], [0.5]], np.float32)
yb = xb @ w_true

loss_val = None
for step in range(start, TOTAL):
    if rank == 1 and step == 6 and not os.path.exists(marker):
        open(marker, "w").close()
        os._exit(7)        # simulated hardware failure AFTER ckpt of step 6
    loss = ((lin(paddle.to_tensor(xb)) - paddle.to_tensor(yb)) ** 2).mean()
    loss.backward()
    if g is not None:
        for p in lin.parameters():
            p.grad = paddle.to_tensor(
                g.all_reduce(np.asarray(p.grad.numpy()), op="sum") / world)
    opt.step()
    opt.clear_grad()
    loss_val = float(loss.numpy())
    if rank == 0:
        tmp = ckpt + ".tmp"
        paddle.save({"model": lin.state_dict(), "opt": opt.state_dict(),
                     "step": step + 1}, tmp)
        os.replace(tmp, ckpt)

if rank == 0:
    with open(out_path, "w") as f:
        f.write(repr(loss_val))
print(f"DRILL_RANK{rank}_DONE loss={loss_val}")
"""


def test_kill_resume_drill_matches_uninterrupted(tmp_path):
    """The composed elastic story (reference fleet/elastic/manager.py:125):
    launcher starts 2 workers training with allreduced grads +
    per-step checkpoints; worker 1 is killed mid-train; the launcher
    re-admits it (restart), it resumes FROM THE CHECKPOINT and rejoins the
    collective mid-stream; the final loss equals an uninterrupted
    single-worker run of the same schedule."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(world, tag, with_kill):
        d = tmp_path / tag
        d.mkdir()
        script = d / "train.py"
        script.write_text(_DRILL_WORKER)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                   REPO_DIR=repo,
                   DRILL_CKPT=str(d / "ckpt.pd"),
                   DRILL_MARKER=str(d / "marker"),
                   DRILL_OUT=str(d / "final_loss.txt"))
        cmd = [sys.executable, "-m", "paddlepaddle_tpu.distributed.launch",
               "--nproc_per_node", str(world), "--max_restarts", "1",
               str(script)]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=300, env=env, cwd=repo)
        assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
        if with_kill:
            assert (d / "marker").exists(), "the kill never fired"
            assert "restart 1/1" in out.stderr
        return float((d / "final_loss.txt").read_text())

    interrupted = run(2, "duo_kill", with_kill=True)
    baseline = run(1, "solo", with_kill=False)
    np.testing.assert_allclose(interrupted, baseline, rtol=1e-6)
