"""Elastic membership (scale up/down) over the native TCPStore.

Reference surface: python/paddle/distributed/fleet/elastic/manager.py:125,
237-316 — hosts register leases, the manager watches membership and rewrites
the world on scale events; plus the launcher relaunch loop.
"""

import time

import pytest

from paddlepaddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                        ElasticNode)
from paddlepaddle_tpu.distributed.store import TCPStore


def _store():
    return TCPStore(is_master=True)


def test_scale_up_commits_new_world():
    store = _store()
    mgr = ElasticManager(store, np_range=(1, 4), heartbeat_timeout=1.0)

    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n0.register()
    mgr.scan_once()
    assert mgr.version == 1 and mgr.members == ["hostA"]

    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n1.register()
    mgr.scan_once()
    assert mgr.version == 2 and mgr.members == ["hostA", "hostB"]

    # workers observe the committed world through the store
    version, members = ElasticManager.read_world(store)
    assert version == 2 and members == ["hostA", "hostB"]
    assert n0.world_changed(1) and not n0.world_changed(2)
    n0.stop()
    n1.stop()


def test_scale_down_on_dead_heartbeat():
    store = _store()
    mgr = ElasticManager(store, np_range=(1, 4), heartbeat_timeout=0.4)
    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n0.register()
    n1.register()
    mgr.scan_once()
    assert sorted(mgr.members) == ["hostA", "hostB"]

    n1.stop()  # hostB stops heartbeating
    deadline = time.time() + 5
    while time.time() < deadline and "hostB" in mgr.members:
        time.sleep(0.1)
        mgr.scan_once()
    assert mgr.members == ["hostA"]  # shrunk world committed
    version, members = ElasticManager.read_world(store)
    assert members == ["hostA"] and version >= 2
    n0.stop()


def test_min_np_floor_blocks_undersized_world():
    store = _store()
    mgr = ElasticManager(store, np_range=(2, 4), heartbeat_timeout=0.3)
    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n0.register()
    n1.register()
    mgr.scan_once()
    assert len(mgr.members) == 2

    n1.stop()
    time.sleep(0.8)
    mgr.scan_once()
    # one alive < min_np=2: the old world stays (job blocks rather than
    # committing an undersized membership)
    assert sorted(mgr.members) == ["hostA", "hostB"]
    n0.stop()


def test_wait_for_np_rendezvous():
    store = _store()
    mgr = ElasticManager(store, np_range=(2, 4), heartbeat_timeout=1.0)
    n0 = ElasticNode(store, "hostA", heartbeat_interval=0.1)
    n0.register()
    with pytest.raises(TimeoutError):
        mgr.wait_for_np(2, timeout=0.5)
    n1 = ElasticNode(store, "hostB", heartbeat_interval=0.1)
    n1.register()
    version, members = mgr.wait_for_np(2, timeout=5)
    assert version >= 1 and sorted(members) == ["hostA", "hostB"]
    n0.stop()
    n1.stop()


def test_max_np_caps_world():
    store = _store()
    mgr = ElasticManager(store, np_range=(1, 2), heartbeat_timeout=1.0)
    nodes = [ElasticNode(store, f"h{i}", heartbeat_interval=0.1)
             for i in range(3)]
    for n in nodes:
        n.register()
    mgr.scan_once()
    assert len(mgr.members) == 2  # capped at max_np
    # surplus nodes must NOT churn the version on every scan (review
    # finding: identical capped world was re-committed each poll)
    v = mgr.version
    for _ in range(5):
        mgr.scan_once()
    assert mgr.version == v
    for n in nodes:
        n.stop()
