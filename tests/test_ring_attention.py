"""Ring attention (context parallel) vs dense reference; recompute tests."""

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; fast tier covers this module via test_fast_smokes.py

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def _dense_attention(q, k, v, causal):
    qf = np.swapaxes(q, 1, 2).astype(np.float64)
    kf = np.swapaxes(k, 1, 2).astype(np.float64)
    vf = np.swapaxes(v, 1, 2).astype(np.float64)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = np.einsum("bhqd,bhkd->bhqk", qf * scale, kf)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((sq, sk), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, vf)
    return np.swapaxes(out, 1, 2)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    import jax
    from jax.sharding import Mesh

    from paddlepaddle_tpu.ops.kernels.ring_attention import ring_attention

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 32, 4, 8
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    out = ring_attention(q, k, v, mesh, sp_axis="sp", causal=causal, data_axis="dp")
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match_dense():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddlepaddle_tpu.ops.kernels.ring_attention import ring_attention

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 16, 2, 4
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "sp"))

    g_ring = jax.grad(lambda q_: jnp.sum(
        ring_attention(q_, k, v, mesh, causal=True, data_axis="dp") ** 2))(q)

    def dense(q_):
        qf = jnp.swapaxes(q_, 1, 2) / np.sqrt(d)
        kf = jnp.swapaxes(k, 1, 2)
        vf = jnp.swapaxes(v, 1, 2)
        sc = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
        mask = jnp.tril(jnp.ones((s, s), bool))
        sc = jnp.where(mask, sc, -1e30)
        p = jax.nn.softmax(sc, axis=-1)
        return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vf), 1, 2)

    g_dense = jax.grad(lambda q_: jnp.sum(dense(q_) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=2e-3, atol=2e-4)


def test_causal_alignment_bottom_right():
    """causal with sq != sk (chunked prefill) is bottom-right aligned: query i
    attends keys j <= i + (sk - sq), matching the reference flash_attention."""
    import jax.numpy as jnp

    from paddlepaddle_tpu.ops.kernels import flash_attention as fa

    rng = np.random.default_rng(0)
    b, h, d, sq, sk = 1, 2, 16, 4, 8
    q = jnp.asarray(rng.standard_normal((b, sq, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, sk, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, sk, h, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    out = np.asarray(fa._xla_attention(q, k, v, True, None, scale))

    # numpy reference with explicit bottom-right mask
    qn = np.swapaxes(np.asarray(q), 1, 2).astype(np.float64)
    kn = np.swapaxes(np.asarray(k), 1, 2).astype(np.float64)
    vn = np.swapaxes(np.asarray(v), 1, 2).astype(np.float64)
    logits = np.einsum("bhqd,bhkd->bhqk", qn, kn) * scale
    mask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
    logits = np.where(mask, logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.swapaxes(np.einsum("bhqk,bhkd->bhqd", p, vn), 1, 2)
    np.testing.assert_allclose(out, ref, atol=1e-4)

    # the Pallas path declines causal sq > sk (no-visible-key rows) so both
    # paths always agree on semantics
    assert fa._pallas_forward(
        jnp.zeros((2, 16, d)), jnp.zeros((2, 8, d)), jnp.zeros((2, 8, d)),
        True, scale) is None


def test_recompute_layer_grads_match():
    from paddlepaddle_tpu.distributed.fleet.recompute import recompute

    paddle.seed(3)
    layer = paddle.nn.Linear(8, 8)
    x = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)

    out = recompute(layer, paddle.to_tensor(x))
    loss = (out ** 2).mean()
    loss.backward()
    g_recompute = layer.weight.grad.numpy().copy()
    layer.clear_gradients()

    out2 = layer(paddle.to_tensor(x))
    ((out2 ** 2).mean()).backward()
    np.testing.assert_allclose(g_recompute, layer.weight.grad.numpy(), rtol=1e-5)


def test_recompute_in_train_step():
    from paddlepaddle_tpu.distributed.fleet.recompute import recompute
    from paddlepaddle_tpu.jit.train import TrainStep
    from paddlepaddle_tpu.optimizer import AdamW

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.block = paddle.nn.Linear(8, 8)
            self.head = paddle.nn.Linear(8, 2)

        def forward(self, x, labels):
            h = recompute(self.block, x)
            return paddle.nn.functional.cross_entropy(self.head(h), labels)

    m = Net()
    opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
    step = TrainStep(m, opt, lambda mm, x, lb: mm(x, lb))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8)).astype(np.float32)
    lb = rng.integers(0, 2, (8,)).astype(np.int64)
    losses = [float(step(x, lb).numpy()) for _ in range(6)]
    assert losses[-1] < losses[0]
