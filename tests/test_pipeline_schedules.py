"""Schedule-driven SPMD pipeline: 1F1B / interleaved-VPP / GPipe executors.

Covers the reference's schedule zoo semantics
(fleet/meta_parallel/pipeline_parallel.py:575 1F1B, :1179 interleaved;
distributed/passes/pipeline_scheduler_pass FThenB/1F1B/VPP): legality of the
instruction tables, the memory/bubble characteristics that distinguish the
schedules, and numerical equivalence of the one-scan executor against a
serial forward/backward reference.
"""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle  # noqa: F401  (conftest forces the CPU mesh)


# ---------------------------------------------------------------------------
# schedule tables
# ---------------------------------------------------------------------------


def test_schedule_legality_sweep():
    from paddlepaddle_tpu.parallel.schedules import build_1f1b, build_gpipe

    for S in (1, 2, 3, 4, 8):
        for M in (1, 2, 4, 8, 16):
            build_gpipe(S, M)
            build_1f1b(S, M)
            for V in (2, 3):
                if M % S == 0:
                    build_1f1b(S, M, V=V)  # validate() raises if illegal


def test_1f1b_memory_and_bubble_vs_gpipe():
    from paddlepaddle_tpu.parallel.schedules import build_1f1b, build_gpipe

    S, M = 4, 8
    gp = build_gpipe(S, M)
    fb = build_1f1b(S, M)
    # same optimal slot count and bubble when t_f == t_b ...
    assert gp.T == fb.T == 2 * (M + S - 1)
    assert gp.stats["bubble_fraction"] == fb.stats["bubble_fraction"]
    # ... but 1F1B holds O(S) activations where GPipe holds O(M)
    assert gp.stash_cap == M
    assert fb.stash_cap == S
    # with more microbatches the gap widens, 1F1B memory stays constant
    assert build_1f1b(S, 32).stash_cap == S
    assert build_gpipe(S, 32).stash_cap == 32


def test_zbh1_beats_1f1b_bubble_at_near_equal_memory():
    """The zero-bubble promise, certified by the exact validator: ZBH1's
    slot-count bubble is strictly below 1F1B's with the activation stash
    capped at S+1 (1F1B uses S). Reference: pipeline_zero_bubble.py:62."""
    from paddlepaddle_tpu.parallel.schedules import build_schedule

    for S, M in [(2, 4), (4, 8), (4, 16), (8, 32)]:
        z = build_schedule("zbh1", S, M)
        o = build_schedule("1f1b", S, M)
        assert z.stats["bubble_fraction"] < o.stats["bubble_fraction"], (S, M)
        assert z.stash_cap <= S + 1, (S, M, z.stash_cap)
        assert z.gstash_cap <= S, (S, M, z.gstash_cap)
        # every microbatch got exactly one F, one BX, one BW per stage
        from paddlepaddle_tpu.parallel.schedules import (OP_BW, OP_BW_LAST,
                                                         OP_BX, OP_BX_LAST,
                                                         OP_F)
        ops = z.ops
        assert (ops == OP_F).sum() == M * S
        assert ((ops == OP_BX) | (ops == OP_BX_LAST)).sum() == M * S
        assert ((ops == OP_BW) | (ops == OP_BW_LAST)).sum() == M * S


def test_zbvpp_beats_vpp_bubble_at_near_equal_memory():
    """ZBVPP = interleaved VPP with split B, the last schedule in the
    reference zoo (pipeline_zero_bubble.py:151): bubble strictly below
    VPP's at the same per-chunk stash bound + 1, with complete F/BX/BW
    coverage certified by the exact validator."""
    from paddlepaddle_tpu.parallel.schedules import (OP_BW, OP_BW_LAST,
                                                     OP_BX, OP_BX_LAST,
                                                     OP_F, build_schedule)

    for S, M, V in [(2, 4, 2), (4, 8, 2), (4, 16, 2), (4, 16, 4), (2, 8, 3)]:
        z = build_schedule("zbvpp", S, M, V)
        v = build_schedule("vpp", S, M, V)
        assert z.stats["bubble_fraction"] < v.stats["bubble_fraction"], (S, M, V)
        assert z.stash_cap <= v.stash_cap + 1, (S, M, V, z.stash_cap)
        ops = z.ops
        G = S * V
        assert (ops == OP_F).sum() == M * G
        assert ((ops == OP_BX) | (ops == OP_BX_LAST)).sum() == M * G
        assert ((ops == OP_BW) | (ops == OP_BW_LAST)).sum() == M * G


def test_validate_rejects_modular_slot_collision():
    """A dependency-legal but out-of-order schedule whose live microbatches
    collide in the executor's m%cap addressing must be rejected, not
    silently corrupt activations (found by review: S=1, F0 F1 B1 F2 B0 B2)."""
    import pytest as _pytest

    from paddlepaddle_tpu.parallel.schedules import (
        OP_B_LAST, OP_F, PipelineSchedule, validate)

    ops = np.array([[OP_F], [OP_F], [OP_B_LAST], [OP_F], [OP_B_LAST],
                    [OP_B_LAST]], np.int32)
    mbs = np.array([[0], [1], [1], [2], [0], [2]], np.int32)
    chunks = np.zeros_like(mbs)
    with _pytest.raises(ValueError, match="collision"):
        validate(PipelineSchedule(S=1, M=3, V=1, ops=ops, mbs=mbs,
                                  chunks=chunks))


def test_build_schedule_rejects_virtual_1f1b():
    import pytest as _pytest

    from paddlepaddle_tpu.parallel.schedules import build_schedule

    with _pytest.raises(ValueError, match="interleaved"):
        build_schedule("1f1b", 4, 8, V=2)


def test_interleaved_shrinks_bubble():
    from paddlepaddle_tpu.parallel.schedules import build_1f1b

    S, M = 4, 8
    b1 = build_1f1b(S, M).stats["bubble_fraction"]
    b2 = build_1f1b(S, M, V=2).stats["bubble_fraction"]
    b4 = build_1f1b(S, M, V=4).stats["bubble_fraction"]
    assert b2 < b1 and b4 < b2  # VPP: ramp ~(S-1)/V


# ---------------------------------------------------------------------------
# executor numerics
# ---------------------------------------------------------------------------

_S, _M, _B, _H = 4, 8, 16, 8


def _mkblock(seed, h=_H):
    r = np.random.default_rng(seed)
    import jax.numpy as jnp

    return {"w": jnp.asarray(r.standard_normal((h, h)) / np.sqrt(h), jnp.float32),
            "b": jnp.asarray(r.standard_normal((h,)) * 0.1, jnp.float32)}


def _block(p, a):
    import jax.numpy as jnp

    return jnp.tanh(a @ p["w"] + p["b"])


def _head_loss(hp, a, y):
    import jax.numpy as jnp

    return jnp.mean((a @ hp["wo"] - y) ** 2)


def _serial(stages, hp, x, y):
    import jax.numpy as jnp

    xm = x.reshape(_M, _B // _M, _H)
    ym = y.reshape(_M, _B // _M, 1)
    tot = 0.0
    for m in range(_M):
        a = xm[m]
        for p in stages:
            a = _block(p, a)
        tot = tot + _head_loss(hp, a, ym[m])
    return tot / _M


@pytest.mark.parametrize("name,V", [("1f1b", 1), ("gpipe", 1),
                                    ("interleaved", 2), ("zbh1", 1),
                                    ("zbvpp", 2)])
def test_pipeline_train_matches_serial(name, V):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddlepaddle_tpu.parallel.pipeline_spmd import (
        spmd_pipeline_train, stack_stage_params, stack_virtual_stage_params)

    rng = np.random.default_rng(0)
    G = V * _S
    stages = [_mkblock(g) for g in range(G)]
    head = {"wo": jnp.asarray(rng.standard_normal((_H, 1)) / np.sqrt(_H), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((_B, _H)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((_B, 1)), jnp.float32)

    ref_loss, (ref_g, ref_hg, ref_dx) = jax.value_and_grad(
        _serial, argnums=(0, 1, 2))(stages, head, x, y)

    stacked = (stack_stage_params(stages) if V == 1
               else stack_virtual_stage_params(stages, _S))
    mesh = Mesh(np.array(jax.devices()).reshape(2, _S), ("dp", "pp"))
    loss, g, hg, dx = spmd_pipeline_train(
        stacked, head, x, y, _block, _head_loss, mesh,
        schedule=name, n_microbatches=_M, num_virtual=V,
        pp_axis="pp", data_axis="dp")

    ref_st = (stack_stage_params(ref_g) if V == 1
              else stack_virtual_stage_params(ref_g, _S))
    assert abs(float(loss) - float(ref_loss)) < 1e-5
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(ref_st[k]),
                                   atol=1e-5)
    np.testing.assert_allclose(np.asarray(hg["wo"]), np.asarray(ref_hg["wo"]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx), atol=1e-5)


def test_pipeline_train_no_data_axis():
    """pp-only mesh (no dp composition) and a PipelineSchedule object."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddlepaddle_tpu.parallel.pipeline_spmd import (
        spmd_pipeline_train, stack_stage_params)
    from paddlepaddle_tpu.parallel.schedules import build_1f1b

    rng = np.random.default_rng(1)
    stages = [_mkblock(g + 10) for g in range(_S)]
    head = {"wo": jnp.asarray(rng.standard_normal((_H, 1)) / np.sqrt(_H), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((_B, _H)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((_B, 1)), jnp.float32)

    ref_loss, (ref_g,) = jax.value_and_grad(_serial, argnums=(0,))(
        stages, head, x, y)
    mesh = Mesh(np.array(jax.devices()[:_S]), ("pp",))
    loss, g, _, _ = spmd_pipeline_train(
        stack_stage_params(stages), head, x, y, _block, _head_loss, mesh,
        schedule=build_1f1b(_S, _M), pp_axis="pp")
    assert abs(float(loss) - float(ref_loss)) < 1e-5
    ref_st = stack_stage_params(ref_g)
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(ref_st["w"]),
                               atol=1e-5)


def test_memory_estimate_matches_executor_buffers():
    """The advertised buffer sizing is the executor's ACTUAL allocation:
    1F1B's stash stays O(S) at realistic activation shapes while GPipe's
    grows O(M), and the estimate enumerates every buffer the scan carries."""
    from paddlepaddle_tpu.parallel.schedules import build_schedule

    S, M = 4, 16
    mb_act = (2, 2048, 4096)            # [mb, seq, hidden] bf16
    g = build_schedule("gpipe", S, M).memory_estimate(mb_act, 2)
    o = build_schedule("1f1b", S, M).memory_estimate(mb_act, 2)
    z = build_schedule("zbh1", S, M).memory_estimate(mb_act, 2)
    act = 2 * 2048 * 4096 * 2
    assert g["stash"] == M * act        # GPipe: all microbatches live
    assert o["stash"] == S * act        # 1F1B: bounded by depth
    assert z["stash"] == (S + 1) * act  # ZBH1: +1 for the deferred BW
    assert z["gstash"] > 0 and o["gstash"] == 0
    for est in (g, o, z):
        assert est["total"] == sum(v for k, v in est.items() if k != "total")
    # the numbers are real memory: a 1F1B stage at these shapes stashes
    # 128 MiB of activations, not something vacuous
    assert o["stash"] == 4 * 32 * 1024 * 1024
