"""RPC, auto-tuner, geometric message passing tests."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def _double(x):
    return x * 2


def _add(a, b):
    return a + b


def test_rpc_sync_async_roundtrip():
    from paddlepaddle_tpu.distributed import rpc

    rpc.init_rpc("worker0", rank=0, world_size=1)
    try:
        info = rpc.get_worker_info("worker0")
        assert info.rank == 0
        assert rpc.rpc_sync("worker0", _double, args=(21,)) == 42
        fut = rpc.rpc_async("worker0", _add, args=(1, 2))
        assert fut.result(timeout=30) == 3
    finally:
        rpc.shutdown()


def test_rpc_exception_propagates():
    from paddlepaddle_tpu.distributed import rpc

    rpc.init_rpc("workerE", rank=0, world_size=1)
    try:
        with pytest.raises(ValueError):
            rpc.rpc_sync("workerE", _raise_value_error)
    finally:
        rpc.shutdown()


def _raise_value_error():
    raise ValueError("intentional")


def test_auto_tuner_candidates_and_pruning():
    from paddlepaddle_tpu.distributed import AutoTuner

    tuner = AutoTuner(num_devices=8, hbm_bytes=16 * 2 ** 30)
    # 7B-ish params cannot fit replicated on 16 GiB -> dp-only pruned away
    ranked = tuner.tune(num_params=7_000_000_000, batch_size=8, seq_len=2048,
                        hidden=4096, layers=32)
    assert ranked, "no surviving config"
    for c in ranked:
        assert c.dp * c.fsdp * c.tp * c.pp == 8
        assert c.est_total_bytes_per_chip <= 16 * 2 ** 30
        assert c.tp * c.fsdp * c.pp > 1  # pure DP impossible at this size
    # a tiny model admits pure dp and it ranks first (pp=1, tp=1)
    ranked_small = tuner.tune(num_params=1_000_000, batch_size=8, seq_len=128,
                              hidden=64, layers=2)
    assert ranked_small[0].pp == 1 and ranked_small[0].tp == 1


def test_geometric_send_u_recv():
    from paddlepaddle_tpu import geometric

    x = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32)
    src = np.array([0, 1, 2, 0], np.int64)
    dst = np.array([1, 2, 1, 0], np.int64)
    out = geometric.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                                paddle.to_tensor(dst), reduce_op="sum")
    expect = np.zeros_like(x)
    for s, d in zip(src, dst):
        expect[d] += x[s]
    np.testing.assert_allclose(out.numpy(), expect)

    out_mean = geometric.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                                     paddle.to_tensor(dst), reduce_op="mean")
    assert np.isfinite(out_mean.numpy()).all()


def test_geometric_segment_ops():
    from paddlepaddle_tpu import geometric

    data = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
    seg = np.array([0, 0, 1, 1], np.int64)
    np.testing.assert_allclose(
        geometric.segment_sum(paddle.to_tensor(data), paddle.to_tensor(seg)).numpy(),
        [[3.0], [7.0]])
    np.testing.assert_allclose(
        geometric.segment_mean(paddle.to_tensor(data), paddle.to_tensor(seg)).numpy(),
        [[1.5], [3.5]])
    np.testing.assert_allclose(
        geometric.segment_max(paddle.to_tensor(data), paddle.to_tensor(seg)).numpy(),
        [[2.0], [4.0]])


def test_geometric_grad():
    from paddlepaddle_tpu import geometric

    x = paddle.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
    src = np.array([0, 1, 2], np.int64)
    dst = np.array([0, 0, 1], np.int64)
    out = geometric.send_u_recv(x, paddle.to_tensor(src), paddle.to_tensor(dst))
    out.sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 2)))


def test_auto_tuner_pruning_reasons_and_model_rules():
    """Shape-divisibility rules prune with recorded reasons (reference
    auto_tuner/prune.py registry)."""
    from paddlepaddle_tpu.distributed import AutoTuner
    from paddlepaddle_tpu.distributed.auto_tuner import ModelSpec

    t = AutoTuner(num_devices=8)
    spec = ModelSpec(num_params=100_000_000, batch_size=8, seq_len=512,
                     hidden=512, layers=6, heads=6, kv_heads=3, vocab=1000)
    cfgs = [t.estimate(c, spec) for c in t.candidates(spec=spec)]
    survivors = t.prune(cfgs, spec=spec)
    pruned = [c for c in cfgs if c.pruned_reason]
    assert survivors and pruned
    # heads=6: tp=4/8 impossible; layers=6: pp=4/8 impossible
    assert all(c.tp in (1, 2) for c in survivors)
    assert all(c.pp in (1, 2, 3) and (c.pp == 1 or 6 % c.pp == 0)
               for c in survivors)
    reasons = " ".join(c.pruned_reason for c in pruned)
    assert "heads" in reasons and "% pp" in reasons


def test_auto_tuner_recorder_resume(tmp_path):
    """Measured trials persist and are not re-run (reference recorder.py)."""
    from paddlepaddle_tpu.distributed import AutoTuner

    hist = str(tmp_path / "trials.jsonl")
    calls = []

    def run_fn(cfg):
        calls.append(cfg.key())
        return 0.01 * (cfg.tp + cfg.pp)

    t = AutoTuner(num_devices=8, history_path=hist)
    best = t.tune(num_params=50_000_000, batch_size=8, seq_len=256,
                  hidden=256, layers=4, run_fn=run_fn, top_k=2)
    assert best and best[0].measured_step_time is not None
    n_first = len(calls)
    assert n_first == 2

    # a new tuner with the same history file resumes: no re-measurement
    t2 = AutoTuner(num_devices=8, history_path=hist)
    best2 = t2.tune(num_params=50_000_000, batch_size=8, seq_len=256,
                    hidden=256, layers=4, run_fn=run_fn, top_k=2)
    assert len(calls) == n_first  # cached
    assert best2[0].key() == best[0].key()
    assert t2.recorder.best()["measured_step_time"] == best[0].measured_step_time


def test_auto_tuner_cost_model_prefers_sharding_for_big_models():
    """For an 8B model the cost model must choose a memory-feasible config
    with tp or fsdp, and estimated step time must be positive and finite."""
    from paddlepaddle_tpu.distributed import AutoTuner

    # the BASELINE north-star scale: Llama-3-8B on 64 chips. On 8x16GB the
    # tuner must (correctly) find NO feasible config — Adam fp32 state alone
    # is 12 GB/chip at full 8-way sharding.
    t8 = AutoTuner(num_devices=8, hbm_bytes=16 * 2 ** 30)
    assert t8.tune(num_params=8_000_000_000, batch_size=16, seq_len=2048,
                   hidden=4096, layers=32, heads=32, kv_heads=8,
                   vocab=128256) == []

    t = AutoTuner(num_devices=64, hbm_bytes=16 * 2 ** 30)
    ranked = t.tune(num_params=8_000_000_000, batch_size=64, seq_len=2048,
                    hidden=4096, layers=32, heads=32, kv_heads=8, vocab=128256)
    assert ranked
    top = ranked[0]
    assert top.fsdp * top.tp * top.pp > 1
    assert 0 < top.est_step_time < 60
    assert top.est_total_bytes_per_chip < 16 * 2 ** 30 * 0.9


def test_auto_tuner_recorder_scoped_by_model(tmp_path):
    """A shared history file must not answer for a different model/topology."""
    from paddlepaddle_tpu.distributed import AutoTuner

    hist = str(tmp_path / "t.jsonl")
    calls = []

    def run_fn(cfg):
        calls.append(cfg.key())
        return 0.01

    t = AutoTuner(num_devices=8, history_path=hist)
    t.tune(num_params=50_000_000, batch_size=8, seq_len=256, hidden=256,
           layers=4, run_fn=run_fn, top_k=1)
    n = len(calls)
    # different model size, same config keys: must re-measure
    t.tune(num_params=100_000_000, batch_size=8, seq_len=256, hidden=256,
           layers=4, run_fn=run_fn, top_k=1)
    assert len(calls) == n + 1


def test_reindex_graph_reference_example():
    """The docstring example from geometric/reindex.py:34, verbatim."""
    import numpy as np

    import paddlepaddle_tpu.geometric as g

    src, dst, nodes = g.reindex_graph(
        np.asarray([0, 1, 2], np.int64),
        np.asarray([8, 9, 0, 4, 7, 6, 7], np.int64),
        np.asarray([2, 3, 2], np.int32))
    np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
    np.testing.assert_array_equal(nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6])


def test_reindex_heter_graph_shared_mapping():
    import numpy as np

    import paddlepaddle_tpu.geometric as g

    srcs, dsts, nodes = g.reindex_heter_graph(
        np.asarray([0, 1], np.int64),
        [np.asarray([5, 0], np.int64), np.asarray([5, 7], np.int64)],
        [np.asarray([1, 1], np.int32), np.asarray([2, 0], np.int32)])
    np.testing.assert_array_equal(nodes.numpy(), [0, 1, 5, 7])
    np.testing.assert_array_equal(srcs[0].numpy(), [2, 0])
    np.testing.assert_array_equal(dsts[0].numpy(), [0, 1])
    np.testing.assert_array_equal(srcs[1].numpy(), [2, 3])
    np.testing.assert_array_equal(dsts[1].numpy(), [0, 0])


def test_sample_neighbors_csc():
    import numpy as np

    import paddlepaddle_tpu.geometric as g

    row = np.asarray([3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9, 7], np.int64)
    colptr = np.asarray([0, 2, 4, 5, 6, 7, 9, 11, 11, 13, 13], np.int64)
    nodes = np.asarray([0, 8, 1, 2], np.int64)
    nb, ct = g.sample_neighbors(row, colptr, nodes, sample_size=2)
    np.testing.assert_array_equal(ct.numpy(), [2, 2, 2, 1])
    # sampled neighbors are actual neighbors of each node
    offs = np.concatenate([[0], np.cumsum(ct.numpy())])
    for i, v in enumerate(nodes):
        got = set(nb.numpy()[offs[i]:offs[i + 1]])
        allowed = set(row[colptr[v]:colptr[v + 1]])
        assert got <= allowed, (v, got, allowed)
    # sample_size=-1 returns all neighbors
    nb_all, ct_all = g.sample_neighbors(row, colptr, nodes)
    np.testing.assert_array_equal(ct_all.numpy(), [2, 2, 2, 1])
    # eids passthrough
    eids = np.arange(13, dtype=np.int64)
    nb2, ct2, eo = g.sample_neighbors(row, colptr, nodes, sample_size=-1,
                                      eids=eids, return_eids=True)
    np.testing.assert_array_equal(eo.numpy(), [0, 1, 11, 12, 2, 3, 4])


def test_weighted_sample_neighbors_prefers_heavy_edges():
    import numpy as np

    import paddlepaddle_tpu.geometric as g

    # node 0 has 4 neighbors; weight mass concentrated on edges 2,3
    row = np.asarray([10, 11, 12, 13], np.int64)
    colptr = np.asarray([0, 4], np.int64)
    w = np.asarray([1e-6, 1e-6, 1.0, 1.0], np.float32)
    hits = {10: 0, 11: 0, 12: 0, 13: 0}
    for _ in range(30):
        nb, ct = g.weighted_sample_neighbors(row, colptr, w,
                                             np.asarray([0], np.int64),
                                             sample_size=2)
        for v in nb.numpy():
            hits[int(v)] += 1
    assert hits[12] + hits[13] > hits[10] + hits[11]


def test_send_uv_edge_messages():
    import numpy as np

    import paddlepaddle_tpu.geometric as g

    x = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
    y = np.asarray([[10.0, 10.0], [20.0, 20.0]], np.float32)
    src = np.asarray([0, 1], np.int32)
    dst = np.asarray([1, 0], np.int32)
    out = g.send_uv(x, y, src, dst, message_op="add")
    np.testing.assert_allclose(out.numpy(), [[21.0, 22.0], [13.0, 14.0]])
