"""RPC, auto-tuner, geometric message passing tests."""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


def _double(x):
    return x * 2


def _add(a, b):
    return a + b


def test_rpc_sync_async_roundtrip():
    from paddlepaddle_tpu.distributed import rpc

    rpc.init_rpc("worker0", rank=0, world_size=1)
    try:
        info = rpc.get_worker_info("worker0")
        assert info.rank == 0
        assert rpc.rpc_sync("worker0", _double, args=(21,)) == 42
        fut = rpc.rpc_async("worker0", _add, args=(1, 2))
        assert fut.result(timeout=30) == 3
    finally:
        rpc.shutdown()


def test_rpc_exception_propagates():
    from paddlepaddle_tpu.distributed import rpc

    rpc.init_rpc("workerE", rank=0, world_size=1)
    try:
        with pytest.raises(ValueError):
            rpc.rpc_sync("workerE", _raise_value_error)
    finally:
        rpc.shutdown()


def _raise_value_error():
    raise ValueError("intentional")


def test_auto_tuner_candidates_and_pruning():
    from paddlepaddle_tpu.distributed import AutoTuner

    tuner = AutoTuner(num_devices=8, hbm_bytes=16 * 2 ** 30)
    # 7B-ish params cannot fit replicated on 16 GiB -> dp-only pruned away
    ranked = tuner.tune(num_params=7_000_000_000, batch_size=8, seq_len=2048,
                        hidden=4096, layers=32)
    assert ranked, "no surviving config"
    for c in ranked:
        assert c.dp * c.fsdp * c.tp * c.pp == 8
        assert c.est_total_bytes_per_chip <= 16 * 2 ** 30
        assert c.tp * c.fsdp * c.pp > 1  # pure DP impossible at this size
    # a tiny model admits pure dp and it ranks first (pp=1, tp=1)
    ranked_small = tuner.tune(num_params=1_000_000, batch_size=8, seq_len=128,
                              hidden=64, layers=2)
    assert ranked_small[0].pp == 1 and ranked_small[0].tp == 1


def test_geometric_send_u_recv():
    from paddlepaddle_tpu import geometric

    x = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32)
    src = np.array([0, 1, 2, 0], np.int64)
    dst = np.array([1, 2, 1, 0], np.int64)
    out = geometric.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                                paddle.to_tensor(dst), reduce_op="sum")
    expect = np.zeros_like(x)
    for s, d in zip(src, dst):
        expect[d] += x[s]
    np.testing.assert_allclose(out.numpy(), expect)

    out_mean = geometric.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                                     paddle.to_tensor(dst), reduce_op="mean")
    assert np.isfinite(out_mean.numpy()).all()


def test_geometric_segment_ops():
    from paddlepaddle_tpu import geometric

    data = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
    seg = np.array([0, 0, 1, 1], np.int64)
    np.testing.assert_allclose(
        geometric.segment_sum(paddle.to_tensor(data), paddle.to_tensor(seg)).numpy(),
        [[3.0], [7.0]])
    np.testing.assert_allclose(
        geometric.segment_mean(paddle.to_tensor(data), paddle.to_tensor(seg)).numpy(),
        [[1.5], [3.5]])
    np.testing.assert_allclose(
        geometric.segment_max(paddle.to_tensor(data), paddle.to_tensor(seg)).numpy(),
        [[2.0], [4.0]])


def test_geometric_grad():
    from paddlepaddle_tpu import geometric

    x = paddle.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
    src = np.array([0, 1, 2], np.int64)
    dst = np.array([0, 0, 1], np.int64)
    out = geometric.send_u_recv(x, paddle.to_tensor(src), paddle.to_tensor(dst))
    out.sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 2)))
