"""The op-graph static Program (r5: static/program.py).

Covers the reference's canonical static workflows (test/book fit-a-line /
recognize-digits shapes, python/paddle/base/backward.py append_backward,
framework.py Program.clone) against the jaxpr-backed IR: real op lists,
real graph transforms, single-jit execution, StableHLO inference export.
"""

import numpy as np
import pytest

import paddlepaddle_tpu as paddle


@pytest.fixture
def static_mode():
    paddle.enable_static()
    try:
        yield
    finally:
        paddle.disable_static()


def _build_linreg():
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
        y = paddle.static.data(name="y", shape=[None, 1], dtype="float32")
        pred = paddle.static.nn.fc(x, size=1)
        loss = ((pred - y) ** 2).mean()
    return prog, x, y, pred, loss


def test_program_is_a_real_op_graph(static_mode):
    prog, x, y, pred, loss = _build_linreg()
    block = prog.global_block()
    assert len(block.ops) >= 3            # fc + sub + pow + mean ops
    types = [op.type for op in block.ops]
    assert "fc_tensordot" in types
    # variables are named and inspectable; ops print like a program listing
    assert isinstance(pred, paddle.static.Variable)
    assert pred.name in block.vars
    text = str(prog)
    assert "fc_tensordot" in text and "Program" in text
    # the program lists its parameters (W, b)
    params = prog.all_parameters()
    assert len(params) == 2
    # variables carry abstract values only — reading raises with the story
    with pytest.raises(RuntimeError, match="graph-build time"):
        pred.numpy()


def test_append_backward_appends_real_grad_ops(static_mode):
    prog, x, y, pred, loss = _build_linreg()
    n_fwd = len(prog.global_block().ops)
    with paddle.static.program_guard(prog):
        pairs = paddle.static.append_backward(loss)
    assert len(prog.global_block().ops) == n_fwd + 1
    back_op = prog.global_block().ops[-1]
    assert back_op.role == "backward"
    assert len(pairs) == 2                 # W and b
    for p, g in pairs:
        assert g.name.endswith("@GRAD")
        assert list(g.aval.shape) == list(p.shape)
    # grad vars are FETCHABLE and numerically right: d/dW mean((xW+b-y)^2)
    exe = paddle.static.Executor()
    rng = np.random.default_rng(0)
    xb = rng.standard_normal((8, 4)).astype(np.float32)
    yb = rng.standard_normal((8, 1)).astype(np.float32)
    (gw, gb) = exe.run(prog, feed={"x": xb, "y": yb},
                       fetch_list=[pairs[0][1], pairs[1][1]])
    W = np.asarray(pairs[0][0].numpy())
    b = np.asarray(pairs[1][0].numpy())
    r = xb @ W + b - yb
    want_gw = 2 * xb.T @ r / r.size
    want_gb = 2 * r.mean(0)
    np.testing.assert_allclose(gw, want_gw, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gb, want_gb, rtol=1e-4, atol=1e-5)


def test_book_fit_a_line_trains_and_infers(static_mode):
    """The reference's canonical train-then-infer workflow, unchanged:
    program_guard build, minimize, executor loop, clone(for_test),
    save_inference_model, load_inference_model."""
    prog, x, y, pred, loss = _build_linreg()
    with paddle.static.program_guard(prog):
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    rng = np.random.default_rng(0)
    w_true = np.asarray([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    losses = []
    for _ in range(30):
        xb = rng.standard_normal((16, 4)).astype(np.float32)
        out, = exe.run(prog, feed={"x": xb, "y": xb @ w_true},
                       fetch_list=[loss])
        losses.append(float(out))
    assert losses[-1] < losses[0] * 0.1, losses[::8]

    # test clone: same vars, forward-only op list
    test_prog = prog.clone(for_test=True)
    assert all(op.role == "forward" for op in test_prog.global_block().ops)
    xq = np.ones((3, 4), np.float32)
    out2, = exe.run(test_prog, feed={"x": xq}, fetch_list=[pred])
    np.testing.assert_allclose(out2, out2[0][None].repeat(3, 0), rtol=1e-5)


def test_save_load_inference_model(static_mode, tmp_path):
    prog, x, y, pred, loss = _build_linreg()
    exe = paddle.static.Executor()
    path = str(tmp_path / "fit_a_line")
    paddle.static.save_inference_model(path, [x], [pred], exe, program=prog)
    loaded, feed_names, fetch_targets = \
        paddle.static.load_inference_model(path, exe)
    assert feed_names == ["x"]
    xq = np.random.default_rng(1).standard_normal((5, 4)).astype(np.float32)
    got, = exe.run(loaded, feed={"x": xq}, fetch_list=fetch_targets)
    want, = exe.run(prog, feed={"x": xq, "y": np.zeros((5, 1), np.float32)},
                    fetch_list=[pred])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_clone_for_test_strips_dropout(static_mode):
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 8], dtype="float32")
        h = paddle.nn.functional.dropout(x, p=0.5, training=True)
        out = h * 2.0
    exe = paddle.static.Executor()
    xb = np.ones((4, 8), np.float32)
    train_out, = exe.run(prog, feed={"x": xb}, fetch_list=[out])
    assert (train_out == 0).any()          # the train run really masks
    test_prog = prog.clone(for_test=True)
    test_out, = exe.run(test_prog, feed={"x": xb}, fetch_list=[out])
    np.testing.assert_allclose(test_out, xb * 2.0)   # identity at eval
    # the substituted op is marked is_test, like the reference attr flip
    drop_op = next(op for op in test_prog.global_block().ops
                   if op.type == "dropout")
    assert drop_op.attrs.get("is_test") is True


def test_batch_norm_state_writes_and_test_clone(static_mode):
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 3], dtype="float32")
        out = paddle.static.nn.batch_norm(x, momentum=0.5)
    exe = paddle.static.Executor()
    rng = np.random.default_rng(0)
    xb = (rng.standard_normal((32, 3)) * 2 + 5).astype(np.float32)
    exe.run(prog, feed={"x": xb}, fetch_list=[out])
    # running stats moved toward the batch stats (state write applied)
    bn_stats = [w[0] for w in prog._state_writes]
    rm, rv = bn_stats[0], bn_stats[1]
    want_rm = 0.5 * np.zeros(3) + 0.5 * xb.mean(0)
    np.testing.assert_allclose(rm.numpy(), want_rm, rtol=1e-4)
    # a SECOND train run keeps moving them
    exe.run(prog, feed={"x": xb}, fetch_list=[out])
    np.testing.assert_allclose(
        rm.numpy(), 0.5 * want_rm + 0.5 * xb.mean(0), rtol=1e-4)

    # test clone: uses running stats, does NOT update them
    test_prog = prog.clone(for_test=True)
    before = rm.numpy().copy()
    t_out, = exe.run(test_prog, feed={"x": xb}, fetch_list=[out])
    np.testing.assert_allclose(rm.numpy(), before)
    scale = 1.0 / np.sqrt(rv.numpy() + 1e-5)
    want = (xb - rm.numpy()) * scale       # gamma=1, beta=0
    np.testing.assert_allclose(t_out, want, rtol=1e-3, atol=1e-3)


def test_intermediate_fetch(static_mode):
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
        h = paddle.nn.functional.relu(x - 0.5)
        out = h.sum()
    exe = paddle.static.Executor()
    xb = np.linspace(0, 1, 8, dtype=np.float32).reshape(2, 4)
    hv, ov = exe.run(prog, feed={"x": xb}, fetch_list=[h, out])
    np.testing.assert_allclose(hv, np.maximum(xb - 0.5, 0), rtol=1e-6)
    np.testing.assert_allclose(ov, hv.sum(), rtol=1e-6)


def test_static_gradients_wrt_feed(static_mode):
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 3], dtype="float32")
        y = (x * x).sum()
        gx, = paddle.static.gradients([y], [x])
    assert gx.name == "x@GRAD"
    exe = paddle.static.Executor()
    xb = np.arange(6, dtype=np.float32).reshape(2, 3)
    gv, = exe.run(prog, feed={"x": xb}, fetch_list=[gx])
    np.testing.assert_allclose(gv, 2 * xb, rtol=1e-6)


def test_serialize_deserialize_program(static_mode):
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
        out = paddle.nn.functional.sigmoid(
            paddle.static.nn.fc(x, size=2))
    blob = paddle.static.serialize_program(prog, fetch_vars=[out])
    assert isinstance(blob, bytes) and len(blob) > 100
    prog2 = paddle.static.deserialize_program(blob)
    exe = paddle.static.Executor()
    xb = np.random.default_rng(2).standard_normal((3, 4)).astype(np.float32)
    got, = exe.run(prog2, feed={"x": xb}, fetch_list=[0])
    want, = exe.run(prog, feed={"x": xb}, fetch_list=[out])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_batch_polymorphic_execution(static_mode):
    """None dims are captured at placeholder 1 but ops record
    shape-polymorphic callables — any fed batch size runs."""
    prog, x, y, pred, loss = _build_linreg()
    exe = paddle.static.Executor()
    for bs in (1, 7, 32):
        out, = exe.run(
            prog, feed={"x": np.ones((bs, 4), np.float32),
                        "y": np.zeros((bs, 1), np.float32)},
            fetch_list=[pred])
        assert out.shape == (bs, 1)


def test_minimize_with_momentum_optimizer(static_mode):
    """minimize works for stateful optimizers too (slots live on the
    optimizer, updates applied from the fetched grads)."""
    prog, x, y, pred, loss = _build_linreg()
    with paddle.static.program_guard(prog):
        opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9)
        opt.minimize(loss)
    exe = paddle.static.Executor()
    rng = np.random.default_rng(3)
    w_true = np.asarray([[2.0], [-1.0], [0.0], [1.0]], np.float32)
    losses = []
    for _ in range(30):
        xb = rng.standard_normal((16, 4)).astype(np.float32)
        out, = exe.run(prog, feed={"x": xb, "y": xb @ w_true},
                       fetch_list=[loss])
        losses.append(float(out))
    assert losses[-1] < losses[0] * 0.1, losses[::8]


def test_fetching_parameters_sees_updates(static_mode):
    """A fetched CONCRETE tensor (parameter/running stat) must be a
    run-time argument of the compiled program, not a trace-time constant —
    otherwise every fetch after the first returns the initial value
    (r5 review finding)."""
    prog, x, y, pred, loss = _build_linreg()
    W = prog.all_parameters()[0]
    with paddle.static.program_guard(prog):
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = paddle.static.Executor()
    rng = np.random.default_rng(0)
    xb = rng.standard_normal((16, 4)).astype(np.float32)
    yb = rng.standard_normal((16, 1)).astype(np.float32)
    _, w1 = exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss, W])
    _, w2 = exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss, W])
    assert not np.allclose(w1, w2), "fetched W must track optimizer steps"
    np.testing.assert_allclose(w2, W.numpy(), rtol=1e-6)


def test_deserialized_program_binds_feeds_by_name(static_mode):
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        a = paddle.static.data(name="a", shape=[None, 2], dtype="float32")
        b = paddle.static.data(name="b", shape=[None, 2], dtype="float32")
        out = a * 2.0 + b
    blob = paddle.static.serialize_program(prog, fetch_vars=[out])
    prog2 = paddle.static.deserialize_program(blob)
    exe = paddle.static.Executor()
    av = np.ones((3, 2), np.float32)
    bv = np.full((3, 2), 10.0, np.float32)
    # reversed dict order must still bind by NAME
    got, = exe.run(prog2, feed={"b": bv, "a": av}, fetch_list=[0])
    np.testing.assert_allclose(got, av * 2 + bv)


def test_static_dropout_masks_vary_across_runs(static_mode):
    """A captured dropout must draw a fresh mask each Executor.run (the
    key is a per-run feed, not a build-time closure constant)."""
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 64], dtype="float32")
        out = paddle.nn.functional.dropout(x, p=0.5, training=True)
    exe = paddle.static.Executor()
    xb = np.ones((8, 64), np.float32)
    m1, = exe.run(prog, feed={"x": xb}, fetch_list=[out])
    m2, = exe.run(prog, feed={"x": xb}, fetch_list=[out])
    assert (m1 != m2).any(), "same dropout mask on consecutive runs"


def test_append_backward_no_grad_set_without_parameter_list(static_mode):
    prog, x, y, pred, loss = _build_linreg()
    W, b = prog.all_parameters()
    with paddle.static.program_guard(prog):
        pairs = paddle.static.append_backward(loss, no_grad_set={W})
    assert [p for p, _ in pairs] == [b]


def test_gradients_rejects_unimplemented_args(static_mode):
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 2], dtype="float32")
        y = (x * x).sum()
        with pytest.raises(NotImplementedError, match="target_gradients"):
            paddle.static.gradients([y], [x], target_gradients=[y])


def test_clone_keeps_feed_vars_resolvable(static_mode):
    prog, x, y, pred, loss = _build_linreg()
    test_prog = prog.clone(for_test=True)
    assert test_prog.global_block().var("x") is x
    assert any(v.name == "x" for v in test_prog.list_vars())


def test_completion_inspects_propagated_shardings(static_mode):
    """The completion pass (reference auto_parallel/static/completion.py
    role): annotate ONE feed, read back the GSPMD-inferred placement of
    every program variable on a CPU mesh."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from paddlepaddle_tpu.distributed.auto_parallel import (
        complete_program, format_completion)

    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[32, 16], dtype="float32")
        h = paddle.static.nn.fc(x, size=8, activation="relu")
        out = h.sum()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    specs = complete_program(prog, mesh,
                             feed_shardings={"x": P("dp", None)})
    # the batch sharding propagates through fc+relu to h
    h_spec = specs[h.name]
    assert tuple(h_spec)[0] == "dp", specs
    # ...but collapses at the scalar reduction
    assert specs[out.name] == P()
    text = format_completion(prog, specs)
    assert "fc_tensordot" in text and "dp" in text


def test_export_inference_model_with_dropout(static_mode, tmp_path):
    """Regression (r6): clone(for_test=True) kept the reserved __rng__ feed
    on substituted eval ops, so save_inference_model demanded a feed the
    user can't supply — KeyError '__rng__' on ANY dropout model."""
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[None, 8], dtype="float32")
        h = paddle.nn.functional.dropout(x, p=0.5, training=True)
        pred = paddle.static.nn.fc(h, size=3)
    exe = paddle.static.Executor()
    path = str(tmp_path / "dropout_model")
    paddle.static.save_inference_model(path, [x], [pred], exe, program=prog)
    loaded, feed_names, fetch_targets = \
        paddle.static.load_inference_model(path, exe)
    assert feed_names == ["x"]            # the rng feed must NOT leak out
    xq = np.random.default_rng(3).standard_normal((4, 8)).astype(np.float32)
    got, = exe.run(loaded, feed={"x": xq}, fetch_list=fetch_targets)
    # eval form: dropout is identity, so export == fc(x) with train masks off
    test_prog = prog.clone(for_test=True)
    assert "__rng__" not in test_prog._feed_targets
    want, = exe.run(test_prog, feed={"x": xq}, fetch_list=[pred])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_two_dropouts_off_same_activation_differ(static_mode):
    """Regression (r6): the per-op rng salt was id(x) of the INPUT variable,
    so two dropout branches off the same activation folded identical keys —
    byte-identical masks. The salt is now unique per captured op."""
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[64, 64], dtype="float32")
        a = paddle.nn.functional.dropout(x, p=0.5, training=True)
        b = paddle.nn.functional.dropout(x, p=0.5, training=True)
    exe = paddle.static.Executor()
    ra, rb = exe.run(prog, feed={"x": np.ones((64, 64), np.float32)},
                     fetch_list=[a, b])
    assert (ra == 0).any() and (rb == 0).any()    # both really mask
    assert not np.array_equal(ra, rb)             # but independently


def test_executor_run_accepts_fetch_names(static_mode):
    """The book-style exe.run(fetch_list=[loss.name]) form resolves names
    through the global block instead of an opaque jit TypeError."""
    prog, x, y, pred, loss = _build_linreg()
    exe = paddle.static.Executor()
    feed = {"x": np.ones((2, 4), np.float32),
            "y": np.zeros((2, 1), np.float32)}
    by_var, = exe.run(prog, feed=feed, fetch_list=[loss])
    by_name, = exe.run(prog, feed=feed, fetch_list=[loss.name])
    np.testing.assert_allclose(by_name, by_var)
    # persistable PARAMETERS resolve by name too (they are concrete op-input
    # tensors, not block variables — the reference executor finds both)
    param = prog.all_parameters()[0]
    got, = exe.run(prog, feed=feed, fetch_list=[param.name])
    np.testing.assert_allclose(got, param.numpy())
    with pytest.raises(ValueError, match="matches no variable"):
        exe.run(prog, feed=feed, fetch_list=["no_such_var"])


def test_exec_cache_pins_fetch_vars(static_mode):
    """Regression (r6): the executable-cache key uses id(fetch_var); a
    GC'd fetch target's recycled id() must never serve a stale compiled
    program. The cache entry now pins its fetch vars: same-id aliasing is
    impossible while the entry lives."""
    import gc

    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data(name="x", shape=[2, 2], dtype="float32")
        a = x * 2.0
    exe = paddle.static.Executor()
    feed = {"x": np.ones((2, 2), np.float32)}
    exe.run(prog, feed=feed, fetch_list=[a])
    pinned_ids = {id(v) for entry in prog._exec_cache.values()
                  for v in entry[4]}
    assert id(a) in pinned_ids
    del a
    gc.collect()
    # the entry still holds the var: its id cannot be recycled into a new
    # variable that would alias the cached program
    assert all(len(entry) == 5 for entry in prog._exec_cache.values())
